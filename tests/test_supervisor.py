"""Elastic restart supervision + multi-host failure detection
(photon_tpu/supervisor.py): the rebuild's replacement for the Spark-inherited
task-retry / executor-loss recovery (SURVEY.md §5.3)."""
import os
import time

import numpy as np
import pytest

from photon_tpu.checkpoint import CheckpointManager
from photon_tpu.supervisor import (
    Heartbeat,
    RestartPolicy,
    RestartsExhausted,
    run_with_recovery,
)


class FlakyRuntime(RuntimeError):
    pass


def test_retries_transient_then_succeeds():
    calls = []

    def attempt(i):
        calls.append(i)
        if len(calls) < 3:
            raise FlakyRuntime(f"transient #{len(calls)}")
        return "done"

    sleeps = []
    out = run_with_recovery(
        attempt,
        RestartPolicy(max_restarts=3, backoff_seconds=0.5, jitter=False),
        sleep=sleeps.append,
    )
    assert out == "done"
    assert calls == [0, 1, 2]
    assert sleeps == [0.5, 1.0]  # exponential backoff between attempts


def test_backoff_jitter_bounds_and_determinism():
    """Decorrelated jitter (the default): every delay stays within
    [backoff, min(cap, 3 * previous)], the stream is reproducible for a
    pinned seed, and different seeds decorrelate (the anti-thundering-herd
    property multi-host restarts need)."""
    policy = RestartPolicy(
        backoff_seconds=1.0, max_backoff_seconds=8.0, seed=7,
    )
    assert policy.jitter  # jitter is the default
    gen = policy.delays()
    delays = [next(gen) for _ in range(8)]
    prev = policy.backoff_seconds
    for d in delays:
        assert policy.backoff_seconds <= d <= min(8.0, 3.0 * prev) + 1e-9
        prev = d
    assert max(delays) <= 8.0  # cap respected
    # Deterministic for the same seed…
    gen2 = policy.delays()
    assert [next(gen2) for _ in range(8)] == delays
    # …and decorrelated across seeds (different hosts restart apart).
    import dataclasses as _dc

    other = _dc.replace(policy, seed=8).delays()
    assert [next(other) for _ in range(8)] != delays
    # run_with_recovery actually sleeps the jittered sequence.
    sleeps = []

    def attempt(i):
        raise OSError("flaky")

    with pytest.raises(RestartsExhausted):
        run_with_recovery(
            attempt,
            RestartPolicy(max_restarts=3, backoff_seconds=1.0,
                          max_backoff_seconds=8.0, seed=7),
            sleep=sleeps.append,
        )
    assert sleeps == delays[:3]


def test_fatal_errors_propagate_immediately():
    calls = []

    def attempt(i):
        calls.append(i)
        raise ValueError("config bug")

    with pytest.raises(ValueError, match="config bug"):
        run_with_recovery(attempt, RestartPolicy(max_restarts=5), sleep=lambda s: None)
    assert calls == [0]  # never retried


def test_keyboard_interrupt_not_retried():
    def attempt(i):
        raise KeyboardInterrupt()

    with pytest.raises(KeyboardInterrupt):
        run_with_recovery(attempt, RestartPolicy(max_restarts=5), sleep=lambda s: None)


def test_budget_exhausted_raises_with_history():
    def attempt(i):
        raise OSError(f"io fail {i}")

    with pytest.raises(RestartsExhausted) as ei:
        run_with_recovery(attempt, RestartPolicy(max_restarts=2, backoff_seconds=0),
                          sleep=lambda s: None)
    failures = ei.value.failures
    assert [f.attempt for f in failures] == [0, 1, 2]
    assert all(f.error_type == "OSError" for f in failures)
    assert isinstance(ei.value.__cause__, OSError)


def test_recovery_resumes_from_checkpoint_bit_identical(tmp_path):
    """A training attempt killed mid-run by a retryable failure restarts
    under the supervisor and, resuming from the checkpoint, produces the
    exact final models of an uninterrupted run — the full §5.3 story:
    failure -> restart -> fast-forward -> identical result."""
    from tests.test_checkpoint import _bundle, _configs, _estimator, _final_arrays

    bundle = _bundle()
    ref = _estimator().fit(bundle, _bundle(seed=1), _configs())

    ckdir = str(tmp_path / "ck")

    class PreemptedManager(CheckpointManager):
        """Simulates a host preemption delivered as a runtime error after
        the Nth coordinate-step snapshot. (Uses its own counter — the base
        class's ``fail_after`` raises KeyboardInterrupt, which is fatal to
        the supervisor by design.)"""

        preempt_after = None

        def save(self, step, state, meta=None):
            super().save(step, state, meta)
            self.wait()
            if self.preempt_after is not None and self._saves >= self.preempt_after:
                raise FlakyRuntime("preempted")

    attempts = []

    def attempt(i):
        attempts.append(i)
        # First attempt dies after 3 steps; the retry runs clean. Each
        # attempt opens its own manager on the shared directory, exactly
        # like a restarted driver process.
        mgr = PreemptedManager(ckdir)
        mgr.preempt_after = 3 if i == 0 else None
        try:
            return _estimator().fit(bundle, _bundle(seed=1), _configs(),
                                    checkpoint_manager=mgr)
        finally:
            mgr._queue.put(None)  # stop writer without re-raising

    resumed = run_with_recovery(
        attempt, RestartPolicy(max_restarts=2, backoff_seconds=0),
        sleep=lambda s: None,
    )
    assert attempts == [0, 1]
    for a, b in zip(_final_arrays(resumed), _final_arrays(ref)):
        np.testing.assert_array_equal(a, b)


def test_driver_max_restarts_flag(tmp_path, monkeypatch):
    """--max-restarts rides through a transient estimator failure."""
    from photon_tpu.cli import game_training_driver
    from photon_tpu.estimators.game_estimator import GameEstimator
    from tests.test_drivers import _write_game_avro

    d = tmp_path / "data"
    d.mkdir()
    _write_game_avro(d / "train.avro", seed=1, n_users=4, rows_per_user=12)

    real_fit = GameEstimator.fit
    state = {"failed": False}

    def flaky_fit(self, *a, **kw):
        if not state["failed"]:
            state["failed"] = True
            raise RuntimeError("transient device hiccup")
        return real_fit(self, *a, **kw)

    monkeypatch.setattr(GameEstimator, "fit", flaky_fit)
    summary = game_training_driver.run([
        "--train-data", str(d / "train.avro"),
        "--output-dir", str(tmp_path / "out"),
        "--task", "LOGISTIC_REGRESSION",
        "--feature-shard", "global:features",
        "--coordinate",
        "fixed:type=fixed,shard=global,reg=L2,max_iter=5,reg_weights=1",
        "--checkpoint-dir", str(tmp_path / "ck"),
        "--max-restarts", "1", "--restart-backoff", "0",
        "--devices", "1",
    ])
    assert state["failed"] and summary["n_configs"] == 1


# ---------------------------------------------------------------------------
# Heartbeat / peer detection


def test_heartbeat_detects_stale_and_missing(tmp_path):
    hdir = str(tmp_path / "hb")
    me = Heartbeat(hdir, process_id=0, interval_seconds=0.05)
    peer = Heartbeat(hdir, process_id=1, interval_seconds=0.05)
    me.beat_once()
    peer.beat_once()

    report = me.check_peers([0, 1, 2], max_age_seconds=10.0)
    assert report.alive == [0, 1]
    assert report.missing == [2]
    assert not report.healthy

    # Age out the peer's beat without sleeping: backdate its file mtime.
    old = time.time() - 60.0
    os.utime(os.path.join(hdir, "host-1.hb"), (old, old))
    report = me.check_peers([0, 1], max_age_seconds=1.0)
    assert report.alive == [0]
    assert report.dead == [1]


def test_heartbeat_background_thread(tmp_path):
    hdir = str(tmp_path / "hb")
    with Heartbeat(hdir, process_id=7, interval_seconds=0.02) as hb:
        time.sleep(0.15)
    # Several beats happened and the file parses as JSON.
    import json

    with open(os.path.join(hdir, "host-7.hb")) as f:
        payload = json.load(f)
    assert payload["process_id"] == 7
    assert payload["beats"] >= 2
    assert hb.check_peers([7], max_age_seconds=30.0).healthy
    # Starting the heartbeat installs the map-count gauge so the same
    # number the watchdog warns on is scrapeable from /metrics.
    from photon_tpu.obs.metrics import REGISTRY

    gauge = REGISTRY.gauge_fn("process_memory_maps", lambda: 0.0)
    series = gauge.collect()  # callback gauge reads live /proc/self/maps
    assert series and series[0][1] > 0


def test_driver_fails_fast_on_dead_peer(tmp_path, monkeypatch):
    """With --heartbeat-dir, a retry attempt whose peer host stopped beating
    raises RestartsUselessError (escaping the retry budget) instead of
    re-entering a collective that cannot complete."""
    from photon_tpu.cli import game_training_driver
    from photon_tpu.cli.game_training_driver import RestartsUselessError
    from photon_tpu.estimators.game_estimator import GameEstimator
    from tests.test_drivers import _write_game_avro

    d = tmp_path / "data"
    d.mkdir()
    _write_game_avro(d / "train.avro", seed=1, n_users=4, rows_per_user=12)

    # Pretend this is a 2-process job whose peer (process 1) died long ago.
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # The driver now ALSO starts the live PeerWatchdog for multi-process
    # jobs, whose default action hard-exits the process — correct in
    # production, fatal to pytest. Neutralize it here: this test covers the
    # graceful between-attempts path; the hard abort has its own subprocess
    # test below.
    from photon_tpu import supervisor as sup

    class _NoopWatchdog:
        def start(self):
            return self

        def stop(self):
            pass

    monkeypatch.setattr(
        sup.Heartbeat, "watchdog", lambda self, *a, **k: _NoopWatchdog()
    )
    hdir = tmp_path / "hb"
    hdir.mkdir()
    stale = hdir / "host-1.hb"
    stale.write_text('{"process_id": 1}')
    old = time.time() - 3600
    os.utime(stale, (old, old))

    def always_fail(self, *a, **kw):
        raise RuntimeError("transient-looking failure")

    monkeypatch.setattr(GameEstimator, "fit", always_fail)
    with pytest.raises(RestartsUselessError, match=r"dead=\[1\]"):
        game_training_driver.run([
            "--train-data", str(d / "train.avro"),
            "--output-dir", str(tmp_path / "out"),
            "--task", "LOGISTIC_REGRESSION",
            "--feature-shard", "global:features",
            "--coordinate",
            "fixed:type=fixed,shard=global,reg=L2,max_iter=5,reg_weights=1",
            "--max-restarts", "3", "--restart-backoff", "0",
            "--heartbeat-dir", str(hdir),
            "--devices", "1",
        ])


def test_watchdog_aborts_hung_collective_standin(tmp_path):
    """VERDICT r3 ask #8: a killed fake peer must abort a hung-collective
    stand-in WITHIN the timeout — from the watchdog thread, while the 'main'
    work is still blocked."""
    import threading

    from photon_tpu.supervisor import PeerWatchdog

    hdir = str(tmp_path / "hb")
    me = Heartbeat(hdir, process_id=0, interval_seconds=0.05).start()
    peer = Heartbeat(hdir, process_id=1, interval_seconds=0.05).start()

    hung = threading.Event()  # stand-in for a psum that never returns
    fired = threading.Event()
    reports = []

    def on_dead(report):
        reports.append(report)
        fired.set()
        hung.set()  # "process abort" releases the hung solve

    wd = PeerWatchdog(
        me, expected=[0, 1], check_interval_seconds=0.05,
        max_age_seconds=0.4, grace_checks=2, on_dead=on_dead,
    ).start()
    try:
        # Healthy while both beat: the watchdog must NOT fire.
        assert not hung.wait(0.5)

        peer.stop()  # kill the fake peer mid-"collective"
        t0 = time.monotonic()
        assert hung.wait(5.0), "watchdog never fired on a dead peer"
        took = time.monotonic() - t0
        assert took < 5.0
        assert reports and reports[0].dead == [1]
        assert wd.fired is not None
    finally:
        wd.stop()
        me.stop()
        peer.stop()


def test_watchdog_default_abort_hard_exits_process(tmp_path):
    """The DEFAULT on_dead path must os._exit(WATCHDOG_EXIT_CODE) even while
    the main thread is blocked, and leave a breadcrumb file."""
    import subprocess
    import sys

    from photon_tpu.supervisor import WATCHDOG_EXIT_CODE

    hdir = str(tmp_path / "hb")
    code = f"""
import time, threading
from photon_tpu.supervisor import Heartbeat, PeerWatchdog
me = Heartbeat({hdir!r}, process_id=0, interval_seconds=0.05).start()
# Peer 1 beats once and dies immediately.
Heartbeat({hdir!r}, process_id=1, interval_seconds=0.05).beat_once()
PeerWatchdog(me, [0, 1], check_interval_seconds=0.05,
             max_age_seconds=0.3, grace_checks=2).start()
time.sleep(60)  # hung-collective stand-in; watchdog must kill us first
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    p = subprocess.run(
        [sys.executable, "-c", code], env=env, timeout=30,
        capture_output=True, text=True,
    )
    assert p.returncode == WATCHDOG_EXIT_CODE, (p.returncode, p.stderr[-500:])
    import json as _json

    with open(os.path.join(hdir, "watchdog-abort.host-0.json")) as f:
        crumb = _json.load(f)
    assert crumb["dead"] == [1]


def test_watchdog_startup_grace_for_never_seen_peers(tmp_path):
    """A peer whose heartbeat has NEVER appeared (startup skew, NFS attribute
    caching) must not trip the watchdog inside the startup grace — but a peer
    that was seen and then vanished must."""
    import threading

    from photon_tpu.supervisor import PeerWatchdog

    hdir = str(tmp_path / "hb")
    me = Heartbeat(hdir, process_id=0, interval_seconds=0.05).start()
    fired = threading.Event()
    wd = PeerWatchdog(
        me, expected=[0, 1], check_interval_seconds=0.05,
        max_age_seconds=0.4, grace_checks=2,
        startup_grace_seconds=600.0,  # never-seen peer 1 is forgiven
        on_dead=lambda r: fired.set(),
    ).start()
    try:
        assert not fired.wait(0.6), "fired on a never-seen peer inside grace"
        # Peer appears, then vanishes: now it counts immediately.
        peer = Heartbeat(hdir, process_id=1, interval_seconds=0.05)
        peer.beat_once()
        time.sleep(0.2)  # let the watchdog see it alive
        os.remove(os.path.join(hdir, "host-1.hb"))
        assert fired.wait(5.0), "did not fire on a vanished peer"
        assert wd.fired is not None and wd.fired.missing == [1]
    finally:
        wd.stop()
        me.stop()


def test_attempt_epoch_barrier(tmp_path):
    """A peer wedged in a previous attempt (epoch never advances) must be
    reported as a laggard; a peer that advances passes the barrier."""
    hdir = str(tmp_path / "hb")
    me = Heartbeat(hdir, process_id=0, interval_seconds=0.05)
    peer = Heartbeat(hdir, process_id=1, interval_seconds=0.05)
    me.set_epoch(1)
    peer.set_epoch(0)  # still in attempt 0: wedged in its collective

    laggards = me.wait_for_epoch([0, 1], 1, timeout_seconds=0.3,
                                 poll_seconds=0.05)
    assert laggards == [1]

    # Peer catches up mid-wait: the barrier passes before the timeout.
    import threading

    def advance():
        time.sleep(0.2)
        peer.set_epoch(1)

    t = threading.Thread(target=advance)
    t.start()
    laggards = me.wait_for_epoch([0, 1], 1, timeout_seconds=5.0,
                                 poll_seconds=0.05)
    t.join()
    assert laggards == []
    assert me.peer_epochs([0, 1]) == {0: 1, 1: 1}


def test_driver_epoch_barrier_blocks_lone_retry(tmp_path, monkeypatch):
    """A retry whose peer never advances its attempt epoch (wedged in the
    previous attempt's collective, heartbeat still fresh) must fail fast
    with RestartsUselessError instead of re-entering collectives alone."""
    from photon_tpu.cli import game_training_driver
    from photon_tpu.cli.game_training_driver import RestartsUselessError
    from photon_tpu.estimators.game_estimator import GameEstimator
    from photon_tpu import supervisor as sup
    from tests.test_drivers import _write_game_avro

    d = tmp_path / "data"
    d.mkdir()
    _write_game_avro(d / "train.avro", seed=1, n_users=4, rows_per_user=12)

    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)

    class _NoopWatchdog:
        def start(self):
            return self

        def stop(self):
            pass

    monkeypatch.setattr(
        sup.Heartbeat, "watchdog", lambda self, *a, **k: _NoopWatchdog()
    )
    # Peer 1 heartbeats freshly (so the dead-peer check passes) but stays
    # pinned at epoch 0 — the wedged-in-a-collective signature.
    hdir = tmp_path / "hb"
    peer = sup.Heartbeat(str(hdir), process_id=1, interval_seconds=0.2).start()
    # Shrink the barrier timeout so the test runs in seconds.
    orig_wait = sup.Heartbeat.wait_for_epoch

    def fast_wait(self, expected, epoch, timeout_seconds=30.0, **kw):
        return orig_wait(self, expected, epoch, timeout_seconds=1.0,
                         poll_seconds=0.1)

    monkeypatch.setattr(sup.Heartbeat, "wait_for_epoch", fast_wait)

    def always_fail(self, *a, **kw):
        raise RuntimeError("transient-looking failure")

    monkeypatch.setattr(GameEstimator, "fit", always_fail)
    try:
        with pytest.raises(RestartsUselessError, match="attempt epoch"):
            game_training_driver.run([
                "--train-data", str(d / "train.avro"),
                "--output-dir", str(tmp_path / "out"),
                "--task", "LOGISTIC_REGRESSION",
                "--feature-shard", "global:features",
                "--coordinate",
                "fixed:type=fixed,shard=global,reg=L2,max_iter=5,reg_weights=1",
                "--max-restarts", "3", "--restart-backoff", "0",
                "--heartbeat-dir", str(hdir),
                "--devices", "1",
            ])
    finally:
        peer.stop()


def test_peer_epochs_tolerates_corrupt_and_missing(tmp_path):
    """A torn/corrupt peer file or a missing one reads as epoch -1 (laggard)
    rather than raising mid-barrier."""
    hdir = str(tmp_path / "hb")
    me = Heartbeat(hdir, process_id=0, interval_seconds=0.05)
    me.set_epoch(2)
    with open(os.path.join(hdir, "host-1.hb"), "w") as f:
        f.write("{torn json")
    epochs = me.peer_epochs([0, 1, 2])
    assert epochs == {0: 2, 1: -1, 2: -1}
    assert me.wait_for_epoch([0, 1], 1, timeout_seconds=0.2,
                             poll_seconds=0.05) == [1]


def test_injected_heartbeat_outage_reads_as_dead_peer(tmp_path):
    """Chaos hook heartbeat.beat: a process whose beacon writes start
    failing (sick shared fs) keeps running but its beat goes stale — and
    peers must classify it dead, which is the watchdog's trigger."""
    from photon_tpu.faults import FaultPlan, FaultSpec, active_plan

    hdir = str(tmp_path / "hb")
    me = Heartbeat(hdir, process_id=0, interval_seconds=0.05)
    peer = Heartbeat(hdir, process_id=1, interval_seconds=0.05)
    me.beat_once()
    peer.beat_once()
    plan = FaultPlan(seed=0, specs=[
        FaultSpec(site="heartbeat.beat", error="os",
                  match={"process_id": "1"}),   # only peer 1's fs is sick
    ])
    with active_plan(plan) as inj:
        with pytest.raises(OSError):
            peer.beat_once()
        me.beat_once()                           # unmatched: still beats
    assert inj.fired("heartbeat.beat") == 1
    # Age out the peer's last good beat; the healthy host must see it dead.
    old = time.time() - 60.0
    os.utime(os.path.join(hdir, "host-1.hb"), (old, old))
    me.beat_once()
    report = me.check_peers([0, 1], max_age_seconds=1.0)
    assert report.dead == [1] and report.alive == [0]


# ------------------------------------------------ executable-cache watchdog


def test_map_count_watchdog_reads_live_process():
    from photon_tpu.supervisor import MapCountWatchdog

    wd = MapCountWatchdog()
    out = wd.check()
    assert set(out) == {"maps", "limit", "fraction", "warned"}
    # this very process has mapped libraries, so procfs platforms report a
    # real count; non-procfs platforms report the documented -1 sentinel
    assert out["maps"] == -1 or out["maps"] > 10
    assert out["limit"] > 0


def test_map_count_watchdog_warns_over_threshold(monkeypatch, caplog):
    import logging

    from photon_tpu.supervisor import MapCountWatchdog

    monkeypatch.setattr(MapCountWatchdog, "map_count",
                        staticmethod(lambda: 40_000))
    monkeypatch.setattr(MapCountWatchdog, "map_limit",
                        staticmethod(lambda: 65_530))
    wd = MapCountWatchdog(warn_fraction=0.5, rewarn_seconds=3600.0)
    with caplog.at_level(logging.WARNING, logger="photon_tpu.supervisor"):
        first = wd.check()
        second = wd.check()            # throttled: no second warning yet
    assert first["warned"] and 0.60 < first["fraction"] < 0.62
    assert not second["warned"]
    assert sum("vm.max_map_count" in r.message for r in caplog.records) == 1

    # below threshold: never warns, and the throttle clock is irrelevant
    monkeypatch.setattr(MapCountWatchdog, "map_count",
                        staticmethod(lambda: 10))
    wd2 = MapCountWatchdog(warn_fraction=0.5, rewarn_seconds=0.0)
    assert not wd2.check()["warned"]


def test_map_count_watchdog_rejects_bad_fraction():
    from photon_tpu.supervisor import MapCountWatchdog

    with pytest.raises(ValueError):
        MapCountWatchdog(warn_fraction=0.0)


def test_clear_executable_caches_resets_warm_state():
    """The λ-boundary clear must also forget retrace warm marks — the next
    config's first compiles are expected, not alarms."""
    from photon_tpu.obs import retrace
    from photon_tpu.supervisor import clear_executable_caches

    kernel = "fit_bucket_newton"
    before = retrace.retraces_after_warmup(kernel)  # counters are
    retrace.mark_warm(kernel)                       # process-global: delta
    clear_executable_caches("test")
    retrace.note_trace(kernel)  # would count as a retrace if still warm
    assert retrace.retraces_after_warmup(kernel) == before
    retrace.mark_warm(kernel)
    retrace.note_trace(kernel)  # sanity: warm marks do count
    assert retrace.retraces_after_warmup(kernel) == before + 1
    retrace.clear_warm(kernel)
