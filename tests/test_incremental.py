"""Incremental training with Gaussian priors (SURVEY.md §2.1 PriorDistribution,
§5.4 checkpoint/resume item (c)).

Golden-standard tier: the prior's pull toward the previous posterior must be
exact in the strong-prior limit, correct in the objective's gradients
(finite differences), and end-to-end through estimator + saved/loaded models.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.batch import make_dense_batch
from photon_tpu.functions.objective import GLMObjective
from photon_tpu.functions.prior import PriorDistribution
from photon_tpu.functions.problem import (
    GLMOptimizationProblem,
    VarianceComputationType,
)
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.optim import OptimizerConfig, OptimizerType, RegularizationContext, RegularizationType
from photon_tpu.types import TaskType

L2 = RegularizationContext(RegularizationType.L2)


def _batch(rng, n=120, d=6, task=TaskType.LOGISTIC_REGRESSION):
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    z = x @ w
    if task == TaskType.LOGISTIC_REGRESSION:
        y = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float64)
    else:
        y = z + 0.1 * rng.normal(size=n)
    return make_dense_batch(x, y, dtype=jnp.float64)


def test_prior_gradients_match_finite_differences(rng):
    batch = _batch(rng)
    prior = PriorDistribution.from_model(
        jnp.asarray(rng.normal(size=6)),
        jnp.asarray(0.1 + rng.random(6)),
        incremental_weight=2.5,
    )
    obj = GLMObjective(
        loss=loss_for_task(TaskType.LOGISTIC_REGRESSION),
        l2_weight=0.3,
        prior=prior,
    )
    w = jnp.asarray(rng.normal(size=6))
    v, g = obj.value_and_grad(w, batch)
    assert v == pytest.approx(float(obj.value(w, batch)))
    eps = 1e-6
    for j in range(6):
        wp = w.at[j].add(eps)
        wm = w.at[j].add(-eps)
        fd = (float(obj.value(wp, batch)) - float(obj.value(wm, batch))) / (2 * eps)
        assert g[j] == pytest.approx(fd, rel=1e-4, abs=1e-6)
    # HVP and diagonal include the prior precision
    hv = obj.hessian_vector(w, jnp.ones(6), batch)
    obj_np = dataclasses.replace(obj, prior=None)
    hv_np = obj_np.hessian_vector(w, jnp.ones(6), batch)
    np.testing.assert_allclose(np.asarray(hv - hv_np), np.asarray(prior.precisions))
    dg = obj.hessian_diagonal(w, batch) - obj_np.hessian_diagonal(w, batch)
    np.testing.assert_allclose(np.asarray(dg), np.asarray(prior.precisions))


@pytest.mark.parametrize("opt", [OptimizerType.LBFGS, OptimizerType.TRON])
def test_strong_prior_pins_solution(rng, opt):
    """λ_inc ≫ data curvature: the solution must collapse onto the prior means
    (1e4 vs data-term curvature ~30; larger values exceed what a 25-halving
    backtracking line search can resolve from a zero start)."""
    batch = _batch(rng)
    mu = jnp.asarray(rng.normal(size=6))
    prior = PriorDistribution.from_model(mu, None, incremental_weight=1e4)
    problem = GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION,
        optimizer_type=opt,
        optimizer_config=OptimizerConfig(max_iterations=100),
        prior=prior,
    )
    model, _ = problem.fit(batch, jnp.zeros(6, jnp.float64))
    np.testing.assert_allclose(
        np.asarray(model.coefficients.means), np.asarray(mu), atol=2e-2
    )


def test_zero_weight_prior_is_noop(rng):
    batch = _batch(rng)
    prior = PriorDistribution.from_model(
        jnp.asarray(rng.normal(size=6)), None, incremental_weight=0.0
    )
    base = GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION, regularization=L2, reg_weight=1.0,
        optimizer_config=OptimizerConfig(max_iterations=80),
    )
    m0, _ = base.fit(batch, jnp.zeros(6, jnp.float64))
    m1, _ = dataclasses.replace(base, prior=prior).fit(batch, jnp.zeros(6, jnp.float64))
    np.testing.assert_allclose(
        np.asarray(m0.coefficients.means), np.asarray(m1.coefficients.means),
        atol=1e-8,
    )


def test_incremental_estimator_end_to_end(tmp_path):
    """Train with variances → save → load → retrain incrementally on new
    data; with a strong prior the new model stays near the old one, with a
    weak prior it moves further (reference incremental-training semantics)."""
    from tests.test_estimator import BASE, _bundle, _estimator

    from photon_tpu.index.index_map import build_index_from_features
    from photon_tpu.io.model_io import load_game_model, save_game_model

    rng = np.random.default_rng(0)
    train1, train2 = _bundle(rng), _bundle(rng, seed_shift=5)
    val = _bundle(rng, seed_shift=9)

    est = _estimator(n_sweeps=1)
    cfg_var = {
        cid: dataclasses.replace(c, variance_type=VarianceComputationType.SIMPLE)
        for cid, c in BASE.items()
    }
    first = est.fit(train1, val, [cfg_var])[0]

    index_maps = {
        "global": build_index_from_features(
            [("g", str(j)) for j in range(6)], add_intercept=False),
        "user": build_index_from_features(
            [("u", str(j)) for j in range(40)], add_intercept=False),
    }
    mdir = tmp_path / "m1"
    save_game_model(str(mdir), first.model, index_maps,
                    {"fixed": "global", "perUser": "user"})
    loaded, _ = load_game_model(str(mdir), index_maps)
    # variances survived the roundtrip
    assert loaded["fixed"].model.coefficients.variances is not None
    assert loaded["perUser"].bucket_variances is not None

    def retrain(weight):
        cfg = {
            cid: dataclasses.replace(c, incremental_weight=weight)
            for cid, c in BASE.items()
        }
        return est.fit(train2, val, [cfg], initial_model=loaded)[0]

    strong = retrain(1e6)
    weak = retrain(1e-3)
    w_old = np.asarray(first.model["fixed"].model.coefficients.means)
    d_strong = np.linalg.norm(
        np.asarray(strong.model["fixed"].model.coefficients.means) - w_old)
    d_weak = np.linalg.norm(
        np.asarray(weak.model["fixed"].model.coefficients.means) - w_old)
    assert d_strong < 0.05
    assert d_weak > d_strong * 5
    assert strong.evaluation.values["AUC"] > 0.6


def test_incremental_without_initial_model_errors():
    from tests.test_estimator import BASE, _bundle, _estimator

    rng = np.random.default_rng(0)
    train, val = _bundle(rng), _bundle(rng, seed_shift=1)
    est = _estimator(n_sweeps=1)
    cfg = {
        cid: dataclasses.replace(c, incremental_weight=1.0)
        for cid, c in BASE.items()
    }
    with pytest.raises(ValueError, match="requires an initial_model"):
        est.fit(train, val, [cfg])
