"""Closed-loop control plane (photon_tpu/control/ — docs/control.md).

Coverage per ISSUE: the ledger's journal-contract row shape; the policy
engine's damping guarantees driven with synthetic series and an
injectable clock (hysteresis min-runs, structurally-impossible reversal
inside a lever cooldown, budget exhaustion journaled once); the
autoscaler's banded up/down decisions; and the controller's
observe→decide→actuate→journal loop plus the canary promote/rollback
protocol — all against scripted stub replicas, no accelerator needed.
"""
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from photon_tpu.control import (
    AutoscalePolicy,
    CanaryPolicy,
    ControlLedger,
    ControlPolicy,
    Controller,
    LEDGER_FILENAME,
    Levers,
    PolicyEngine,
    ReplicaTarget,
    Rule,
    promote_wave,
    read_ledger,
)
from photon_tpu.online.delta import EntityPatch, ModelDelta
from photon_tpu.replication import DeltaLogWriter, iter_log, log_next_seq
from photon_tpu.supervisor import RestartPolicy


def _delta(seq, entity="user1", val=0.1):
    return ModelDelta(
        seq=seq,
        patches={"perUser": {entity: EntityPatch(
            key=entity, cols=np.array([0], np.int32),
            vals=np.array([val], np.float32))}},
        event_horizon=seq,
    )


class _Clock:
    """Injectable monotonic clock: cooldown tests never sleep."""

    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------- ledger


def test_ledger_journal_row_contract(tmp_path):
    """Rows carry the PR 15 journal contract (time/t/event/pid) so
    fleet.merge_journals interleaves control rows with recovery rows."""
    path = str(tmp_path / LEDGER_FILENAME)
    ledger = ControlLedger(path)
    ledger.record("controller_started", policy_digest="abc123")
    ledger.record("action", action="shed_cache", target="http://r0")
    rows = ledger.rows()
    assert [r["event"] for r in rows] == ["controller_started", "action"]
    for r in rows:
        assert r["time"].endswith("Z") and "T" in r["time"]
        assert isinstance(r["t"], float)
        assert isinstance(r["pid"], int)
    assert rows[0]["policy_digest"] == "abc123"
    assert rows[1]["action"] == "shed_cache"


def test_ledger_reader_tolerates_garbage(tmp_path):
    path = tmp_path / LEDGER_FILENAME
    ledger = ControlLedger(str(path))
    ledger.record("observation", target="r0")
    with open(path, "a") as f:
        f.write('{"torn": tr')  # crashed-writer tail
    assert [r["event"] for r in read_ledger(str(path))] == ["observation"]
    assert list(read_ledger(str(tmp_path / "absent.jsonl"))) == []


# --------------------------------------------------------- policy engine


def _policy(**kw):
    kw.setdefault("autoscale", None)
    return ControlPolicy(**kw)


def test_policy_json_roundtrip_and_digest(tmp_path):
    p = ControlPolicy()
    q = ControlPolicy.from_json(p.to_json())
    assert q == p and q.digest() == p.digest()
    path = tmp_path / "policy.json"
    path.write_text(p.to_json())
    assert ControlPolicy.from_file(str(path)).digest() == p.digest()
    # Digest is content-addressed: any knob change moves it.
    import dataclasses

    assert dataclasses.replace(p, tick_s=2.0).digest() != p.digest()


def test_policy_rejects_unknown_vocabulary():
    with pytest.raises(ValueError):
        Rule(name="x", signal="nope", kind="flag", action="shed_cache")
    with pytest.raises(ValueError):
        Rule(name="x", signal="errors", kind="vibes", action="shed_cache")
    with pytest.raises(ValueError):
        Rule(name="x", signal="errors", kind="flag", action="format_disk")
    with pytest.raises(ValueError):
        ControlPolicy(rules=(
            Rule(name="dup", signal="errors", kind="flag",
                 action="shed_cache"),
            Rule(name="dup", signal="errors", kind="flag",
                 action="shed_cache"),
        ))


def test_flag_rule_needs_min_run_consecutive():
    """Hysteresis: one bad sample never fires a lever (min_run=2)."""
    policy = _policy(rules=(Rule(
        name="tailer_dead", signal="tailer_dead", kind="flag",
        action="restart_tailer", min_run=2, cooldown_s=10.0, budget=5),))
    eng = PolicyEngine(policy, clock=_Clock())
    eng.observe("r0", {"tailer_dead": 1.0})
    assert eng.decide("r0", {}) == []
    eng.observe("r0", {"tailer_dead": 0.0})   # flicker resets the run
    eng.observe("r0", {"tailer_dead": 1.0})
    assert eng.decide("r0", {}) == []
    eng.observe("r0", {"tailer_dead": 1.0})
    out = eng.decide("r0", {})
    assert [d.action for d in out] == ["restart_tailer"]


def test_threshold_rule_requires_rising_trend():
    """The memory rule fires on TRAJECTORY (high AND rising), not level —
    a stable-high watermark is the guard's steady state, not a ramp."""
    policy = _policy(rules=(Rule(
        name="memory_trend", signal="memory_watermark", kind="threshold",
        action="shed_cache", high=0.75, min_run=2, trend_ticks=3,
        cooldown_s=10.0, budget=5),))
    eng = PolicyEngine(policy, clock=_Clock())
    for v in (0.80, 0.80, 0.80):              # high but flat
        eng.observe("r0", {"memory_watermark": v})
    assert eng.decide("r0", {}) == []
    eng2 = PolicyEngine(policy, clock=_Clock())
    for v in (0.76, 0.82, 0.90):              # high and climbing
        eng2.observe("r0", {"memory_watermark": v})
    out = eng2.decide("r0", {})
    assert [d.action for d in out] == ["shed_cache"]
    assert out[0].evidence["value"] == 0.90


def test_level_shift_rule_fires_only_at_live_edge():
    """A shift that detected ticks ago and re-baselined is history — the
    predicate demands the anomaly be live at the newest sample."""
    rule = Rule(name="latency_shift", signal="probe_latency_ms",
                kind="level_shift", action="standby_swap",
                z_threshold=6.0, window=8, min_history=4, min_run=2,
                cooldown_s=0.0, budget=None)
    policy = _policy(rules=(rule,))
    clock = _Clock()
    eng = PolicyEngine(policy, clock=clock)
    for i in range(6):
        eng.observe("r0", {"probe_latency_ms": 10.0 + (i % 3) * 0.2})
        assert eng.decide("r0", {}) == []
    eng.observe("r0", {"probe_latency_ms": 80.0})
    assert eng.decide("r0", {}) == []          # run of 1: still hysteresis
    eng.observe("r0", {"probe_latency_ms": 82.0})
    fired = eng.decide("r0", {})
    assert [d.action for d in fired] == ["standby_swap"]
    assert fired[0].evidence["z"] >= 6.0
    # Keep feeding the shifted level until the trailing window re-baselines:
    # the rule must go quiet again (no cooldown/budget doing the work here).
    quiet = 0
    for _ in range(12):
        eng.observe("r0", {"probe_latency_ms": 81.0})
        if not eng.decide("r0", {}):
            quiet += 1
    assert quiet >= 4


def test_cooldown_blocks_refire_until_elapsed():
    """No lever refires (in EITHER direction) inside its cooldown — the
    chaos drill's no-reversal property, provable with a fake clock."""
    policy = _policy(rules=(Rule(
        name="tailer_dead", signal="tailer_dead", kind="flag",
        action="restart_tailer", min_run=1, cooldown_s=30.0, budget=None),))
    clock = _Clock()
    eng = PolicyEngine(policy, clock=clock)
    eng.observe("r0", {"tailer_dead": 1.0})
    assert len(eng.decide("r0", {})) == 1
    clock.advance(5.0)
    eng.observe("r0", {"tailer_dead": 1.0})
    assert eng.decide("r0", {}) == []          # suppressed, not fired
    sup = eng.drain_suppressed()
    assert sup and sup[0]["reason"] == "cooldown"
    assert 0 < sup[0]["cooldown_remaining_s"] <= 30.0
    clock.advance(26.0)                        # past the window
    eng.observe("r0", {"tailer_dead": 1.0})
    assert len(eng.decide("r0", {})) == 1
    # Cooldowns are per-target: r1 was never in r0's shadow.
    eng.observe("r1", {"tailer_dead": 1.0})
    assert len(eng.decide("r1", {})) == 1


def test_budget_exhaustion_suppresses_and_flags_once():
    policy = _policy(rules=(Rule(
        name="tailer_dead", signal="tailer_dead", kind="flag",
        action="restart_tailer", min_run=1, cooldown_s=1.0, budget=1),))
    clock = _Clock()
    eng = PolicyEngine(policy, clock=clock)
    eng.observe("r0", {"tailer_dead": 1.0})
    assert len(eng.decide("r0", {})) == 1      # spends the whole budget
    firsts = []
    for _ in range(3):
        clock.advance(5.0)
        eng.observe("r0", {"tailer_dead": 1.0})
        assert eng.decide("r0", {}) == []
        sup = eng.drain_suppressed()
        assert sup[0]["reason"] == "budget"
        firsts.append(sup[0]["first"])
    assert firsts == [True, False, False]      # journaled once, not spammed


def test_autoscale_up_down_and_dead_zone():
    ap = AutoscalePolicy(queue_high=0.75, queue_low=0.25,
                         knee_latency_ms=250.0, min_run=2,
                         max_batch_floor=8, max_batch_ceiling=64,
                         queue_per_batch=4, cooldown_s=20.0, budget=6)
    policy = ControlPolicy(rules=(), autoscale=ap)
    clock = _Clock()
    eng = PolicyEngine(policy, clock=clock)
    ctx = {"max_batch": 16, "max_queue": 64}
    # Saturated queue + latency below the knee -> scale up x2.
    for _ in range(2):
        eng.observe("r0", {"queue_frac": 0.9, "probe_latency_ms": 50.0})
    (d,) = eng.decide("r0", ctx)
    assert d.action == "scale_batcher" and d.rule == "autoscale"
    assert d.params == {"max_batch": 32, "max_queue": 128}
    assert d.evidence["direction"] == "up"
    # Dead zone between the bands: no decision, no suppression noise.
    clock.advance(60.0)
    for _ in range(2):
        eng.observe("r0", {"queue_frac": 0.5, "probe_latency_ms": 300.0})
    assert eng.decide("r0", ctx) == []
    assert eng.drain_suppressed() == []
    # Shallow queue + latency past the knee -> the batch IS the bottleneck.
    clock.advance(60.0)
    for _ in range(2):
        eng.observe("r0", {"queue_frac": 0.1, "probe_latency_ms": 400.0})
    (d,) = eng.decide("r0", ctx)
    assert d.params["max_batch"] == 8 and d.evidence["direction"] == "down"
    # Ceiling/floor clamp: at the floor, down decisions stop entirely.
    clock.advance(60.0)
    for _ in range(2):
        eng.observe("r0", {"queue_frac": 0.1, "probe_latency_ms": 400.0})
    assert eng.decide("r0", {"max_batch": 8}) == []


def test_autoscale_shares_one_cooldown_both_directions():
    """Up then immediately down is a reversal — structurally impossible
    inside the shared (scale_batcher, target) cooldown."""
    ap = AutoscalePolicy(min_run=1, cooldown_s=30.0, budget=None,
                         max_batch_floor=8, max_batch_ceiling=64)
    policy = ControlPolicy(rules=(), autoscale=ap)
    clock = _Clock()
    eng = PolicyEngine(policy, clock=clock)
    eng.observe("r0", {"queue_frac": 0.9, "probe_latency_ms": 50.0})
    (up,) = eng.decide("r0", {"max_batch": 16})
    assert up.evidence["direction"] == "up"
    clock.advance(1.0)
    # Signals now argue DOWN; the cooldown set by the up-action refuses.
    eng.observe("r0", {"queue_frac": 0.1, "probe_latency_ms": 400.0})
    assert eng.decide("r0", {"max_batch": 32}) == []
    assert eng.drain_suppressed()[0]["reason"] == "cooldown"
    clock.advance(30.0)
    eng.observe("r0", {"queue_frac": 0.1, "probe_latency_ms": 400.0})
    (down,) = eng.decide("r0", {"max_batch": 32})
    assert down.evidence["direction"] == "down"


def test_decisions_capped_per_tick():
    policy = _policy(
        rules=(
            Rule(name="a", signal="tailer_dead", kind="flag",
                 action="restart_tailer", min_run=1, cooldown_s=0.0,
                 budget=None),
            Rule(name="b", signal="errors", kind="threshold",
                 action="shed_cache", high=1.0, min_run=1, cooldown_s=0.0,
                 budget=None),
        ),
        max_actions_per_tick=1,
    )
    eng = PolicyEngine(policy, clock=_Clock())
    eng.observe("r0", {"tailer_dead": 1.0, "errors": 5.0})
    assert len(eng.decide("r0", {})) == 1


# ------------------------------------------------------- stub replicas


class _StubControlReplica:
    """A scripted serving replica for controller tests: /healthz,
    /metrics, /score and every admin lever, with call recording."""

    def __init__(self, name, score=1.0):
        self.name = name
        self.score = score
        self.score_delay_s = 0.0
        self.degraded = []
        self.status = "ok"
        self.watermark = 0
        self.memory_watermark = 0.1
        self.queued = 0
        self.max_batch = 16
        self.max_queue = 64
        self.model_version = 1
        self.calls = []          # (endpoint, payload) actuation record
        self.patches = []        # wire deltas taken at /admin/patch
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _read_json(self):
                n = int(self.headers.get("Content-Length") or 0)
                return json.loads(self.rfile.read(n)) if n else {}

            def do_GET(self):
                if self.path == "/healthz":
                    self._reply(200, {
                        "status": stub.status,
                        "degraded": list(stub.degraded),
                        "model_version": stub.model_version,
                        "replication": {"seq_watermark": stub.watermark,
                                        "lag": 0},
                    })
                elif self.path == "/metrics":
                    self._reply(200, {
                        "latency": {"p95_ms": 5.0},
                        "batcher": {"max_batch": stub.max_batch,
                                    "max_queue": stub.max_queue,
                                    "queued": stub.queued},
                        "memory": {"watermark": stub.memory_watermark},
                        "errors": 0,
                    })
                else:
                    self._reply(404, {})

            def do_POST(self):
                payload = self._read_json()
                if self.path == "/score":
                    if stub.score_delay_s:
                        time.sleep(stub.score_delay_s)
                    self._reply(200, {"score": stub.score,
                                      "model_version": stub.model_version})
                    return
                stub.calls.append((self.path, payload))
                if self.path == "/admin/patch":
                    stub.patches.append(payload)
                    self._reply(200, {"patch_seq": len(stub.patches)})
                elif self.path == "/admin/swap":
                    stub.model_version += 1
                    self._reply(200, {"version": stub.model_version})
                elif self.path in ("/admin/standby", "/admin/memory/shed",
                                   "/admin/tune"):
                    self._reply(200, {"ok": True})
                elif self.path == "/admin/replication/restart":
                    self._reply(200, {"restarted": True})
                else:
                    self._reply(404, {})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def url(self):
        h, p = self.httpd.server_address[:2]
        return f"http://{h}:{p}"

    def admin_calls(self, path):
        return [p for (ep, p) in self.calls if ep == path]

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def stub():
    s = _StubControlReplica("r0")
    yield s
    s.close()


def _controller(policy, replicas, tmp_path, **kw):
    ledger = ControlLedger(str(tmp_path / LEDGER_FILENAME))
    return Controller(policy, replicas, ledger, **kw)


# ----------------------------------------------------------- controller


def test_controller_tailer_dead_restart_within_budget(stub, tmp_path):
    """healthz replication_tailer_dead for min_run ticks -> one journaled
    restart POST; the supervisor RestartBudget bounds repeat requests."""
    stub.degraded = ["replication_tailer_dead"]
    policy = _policy(
        tick_s=0.01,
        rules=(Rule(name="tailer_dead", signal="tailer_dead", kind="flag",
                    action="restart_tailer", min_run=2, cooldown_s=0.0,
                    budget=None),))
    ctl = _controller(
        policy, [ReplicaTarget(stub.url)], tmp_path,
        restart_policy=RestartPolicy(max_restarts=1, backoff_seconds=0.0,
                                     jitter=False))
    ctl.tick()
    assert stub.admin_calls("/admin/replication/restart") == []
    ctl.tick()
    assert len(stub.admin_calls("/admin/replication/restart")) == 1
    # Third tick: predicate still holds, cooldown 0 — but the restart
    # BUDGET refuses, journaled as a failed outcome, no HTTP fired.
    ctl.tick()
    assert len(stub.admin_calls("/admin/replication/restart")) == 1
    rows = ctl.ledger.rows()
    outcomes = [r for r in rows if r["event"] == "action_outcome"]
    assert [o["ok"] for o in outcomes] == [True, False]
    assert "budget" in outcomes[1]["error"]
    assert any(r["event"] == "rule_fired" for r in rows)
    assert any(r["event"] == "observation" for r in rows)


def test_controller_memory_ramp_sheds_cache(stub, tmp_path):
    policy = _policy(
        tick_s=0.01,
        rules=(Rule(name="memory_trend", signal="memory_watermark",
                    kind="threshold", action="shed_cache", high=0.75,
                    min_run=2, trend_ticks=3, cooldown_s=60.0, budget=3),))
    ctl = _controller(policy, [ReplicaTarget(stub.url)], tmp_path)
    for w in (0.5, 0.78, 0.85, 0.93):
        stub.memory_watermark = w
        ctl.tick()
    assert len(stub.admin_calls("/admin/memory/shed")) == 1
    # Cooldown holds the lever even as the ramp continues.
    stub.memory_watermark = 0.97
    ctl.tick()
    assert len(stub.admin_calls("/admin/memory/shed")) == 1
    assert any(r["event"] == "action_suppressed"
               and r["reason"] == "cooldown" for r in ctl.ledger.rows())


def test_controller_latency_shift_triggers_standby_swap(stub, tmp_path):
    """The live 8x latency shift: the controller's own probe round-trips
    shift immediately (the server histogram is lifetime-cumulative and
    would take thousands of samples) and the standby+swap lever fires."""
    policy = _policy(
        tick_s=0.01,
        rules=(Rule(name="latency_shift", signal="probe_latency_ms",
                    kind="level_shift", action="standby_swap",
                    z_threshold=6.0, window=8, min_history=4, min_run=2,
                    cooldown_s=60.0, budget=2),))
    ctl = _controller(
        policy, [ReplicaTarget(stub.url)], tmp_path,
        base_model_dir="/models/base",
        probe_rows=[{"features": {}, "entities": {}}])
    for _ in range(6):
        ctl.tick()
    assert stub.admin_calls("/admin/swap") == []
    stub.score_delay_s = 0.25                 # the injected shift
    ctl.tick()
    ctl.tick()
    assert stub.admin_calls("/admin/standby") == [
        {"model_dir": "/models/base"}]
    assert stub.admin_calls("/admin/swap") == [
        {"model_dir": "/models/base"}]
    rows = ctl.ledger.rows()
    fired = [r for r in rows if r["event"] == "rule_fired"]
    assert fired and fired[0]["rule"] == "latency_shift"
    assert fired[0]["z"] >= 6.0


def test_controller_autoscales_batcher_with_damping(stub, tmp_path):
    stub.queued = 60                          # 60/64 ~ 0.94 saturation
    policy = ControlPolicy(
        tick_s=0.01, rules=(),
        autoscale=AutoscalePolicy(min_run=2, cooldown_s=60.0,
                                  max_batch_ceiling=64))
    ctl = _controller(policy, [ReplicaTarget(stub.url)], tmp_path,
                      probe_rows=[{"features": {}, "entities": {}}])
    ctl.tick()
    ctl.tick()
    tunes = stub.admin_calls("/admin/tune")
    assert tunes == [{"max_batch": 32, "max_queue": 128}]
    ctl.tick()                                # cooldown: no second tune
    assert len(stub.admin_calls("/admin/tune")) == 1


def test_controller_unreachable_replica_journaled_not_fatal(tmp_path):
    policy = _policy(tick_s=0.01)
    ctl = _controller(policy, [ReplicaTarget("http://127.0.0.1:1")],
                      tmp_path)
    out = ctl.tick()
    assert out["decisions"] == 0
    rows = ctl.ledger.rows()
    assert rows and rows[0]["event"] == "observation"
    assert "error" in rows[0]


def test_controller_rejects_two_canaries(tmp_path):
    with pytest.raises(ValueError):
        _controller(_policy(), [ReplicaTarget("http://a", canary=True),
                                ReplicaTarget("http://b", canary=True)],
                    tmp_path)
    with pytest.raises(ValueError):
        # Canary mode without the log plumbing is a config error, loudly.
        _controller(_policy(), [ReplicaTarget("http://a", canary=True)],
                    tmp_path)


# ------------------------------------------------------ canary protocol


def _canary_setup(tmp_path, policy=None):
    ref = _StubControlReplica("ref", score=1.0)
    can = _StubControlReplica("can", score=1.0)
    main_log = str(tmp_path / "delta-log.jsonl")
    canary_log = str(tmp_path / "delta-log.canary.jsonl")
    policy = policy or ControlPolicy(
        tick_s=0.01, rules=(), autoscale=None,
        canary=CanaryPolicy(soak_ticks=2, drift_threshold=0.25,
                            settle_ticks=2))
    ctl = _controller(
        policy,
        [ReplicaTarget(ref.url), ReplicaTarget(can.url, canary=True)],
        tmp_path,
        main_log_path=main_log, canary_log_path=canary_log,
        base_model_dir="/models/base",
        probe_rows=[{"features": {}, "entities": {}}])
    return ref, can, main_log, canary_log, ctl


def test_canary_wave_promoted_after_clean_soak(tmp_path):
    ref, can, main_log, canary_log, ctl = _canary_setup(tmp_path)
    try:
        # Controller owns the main log: base marker at seq 0 already.
        assert log_next_seq(main_log) == 1
        ctl.tick()                            # idle: no wave yet
        assert ctl._canary.phase == "idle"
        with DeltaLogWriter(canary_log) as w:
            w.append(_delta(0, val=0.5), trace_id="tw-0")
            w.append(_delta(1, val=0.7))
        can.watermark = 2                     # canary applied the wave
        ctl.tick()                            # soak begins
        ctl.tick()                            # settle check -> soaking+probe
        ctl.tick()                            # probe 2 of 2 -> promote
        rows = ctl.ledger.rows()
        events = [r["event"] for r in rows]
        assert "canary_soak_begin" in events
        assert "canary_promote" in events
        assert "canary_rollback" not in events
        promote = next(r for r in rows if r["event"] == "canary_promote")
        assert promote["main_seqs"] == [1, 2]  # fresh MAINLINE seqs
        probes = [r for r in rows if r["event"] == "canary_probe"]
        assert len(probes) == 2
        assert all(p["drift"] == 0.0 for p in probes)
        recs = [r for r in iter_log(main_log)]
        assert recs[0].is_snapshot
        assert [r.seq for r in recs] == [0, 1, 2]
        # The wave window is consumed: nothing re-adjudicates.
        ctl.tick()
        assert ctl._canary.phase == "idle"
        assert log_next_seq(main_log) == 3
    finally:
        ref.close()
        can.close()


def test_canary_poisoned_wave_rolled_back_and_resynced(tmp_path):
    ref, can, main_log, canary_log, ctl = _canary_setup(tmp_path)
    try:
        # First, promote a good wave so the mainline has real deltas the
        # rollback's resync must restore.
        with DeltaLogWriter(canary_log) as w:
            w.append(_delta(0, val=0.5))
        can.watermark = 1
        ctl.tick()
        ctl.tick()
        ctl.tick()
        assert log_next_seq(main_log) == 2    # base marker + promoted delta
        # Poisoned wave: the canary's scores drift far from the reference.
        with DeltaLogWriter(canary_log) as w:
            w.append(_delta(0, val=99.0))
        can.watermark = 2
        can.score = 9.0                       # drift 8.0 >> 0.25
        ctl.tick()                            # soak begins
        ctl.tick()                            # settle -> probe -> breach
        rows = ctl.ledger.rows()
        rb = [r for r in rows if r["event"] == "canary_rollback"]
        assert len(rb) == 1 and rb[0]["reason"] == "score_drift"
        # Rollback: pointer move to base + resync of the ONE mainline delta.
        assert can.admin_calls("/admin/standby") == [
            {"model_dir": "/models/base"}]
        assert len(can.admin_calls("/admin/swap")) == 1
        resync = next(r for r in rows if r["event"] == "canary_resync")
        assert resync["ok"] is True and resync["deltas"] == 1
        assert len(can.patches) == 1
        # The resynced delta is the GOOD promoted one, not the poison.
        vals = can.patches[0]["patches"]["perUser"]["user1"]["vals"]
        assert vals == pytest.approx([0.5])
        # THE acceptance property: the poisoned wave never reached the main
        # log, so no non-canary replica can ever see it.
        assert log_next_seq(main_log) == 2
        assert ref.patches == []
        assert ref.admin_calls("/admin/swap") == []
    finally:
        ref.close()
        can.close()


def test_canary_unreachable_through_settle_rolls_back(tmp_path):
    ref, can, main_log, canary_log, ctl = _canary_setup(tmp_path)
    can.close()                               # canary down before the wave
    try:
        with DeltaLogWriter(canary_log) as w:
            w.append(_delta(0, val=0.5))
        ctl.tick()                            # soak begins
        ctl.tick()                            # settle 1 (no signals)
        ctl.tick()                            # settle 2 -> verdict
        rows = ctl.ledger.rows()
        rb = [r for r in rows if r["event"] == "canary_rollback"]
        assert len(rb) == 1
        assert rb[0]["reason"] == "canary_unreachable"
        assert log_next_seq(main_log) == 1    # nothing promoted
    finally:
        ref.close()


def test_canary_stalled_wave_rolls_back(tmp_path):
    """A REACHABLE canary whose watermark never reaches the wave (tailer
    stuck or refusing the delta) must not gate the fleet forever: the
    settle window expires into a rollback, not an infinite wait."""
    ref, can, main_log, canary_log, ctl = _canary_setup(tmp_path)
    try:
        with DeltaLogWriter(canary_log) as w:
            w.append(_delta(0, val=0.5))
            w.append(_delta(1, val=0.7))
        # can.watermark stays 0: the canary answers /healthz but its
        # watermark never reaches the wave's last seq (1).
        ctl.tick()                            # soak begins
        ctl.tick()                            # settle 1 (stuck at 0)
        ctl.tick()                            # settle 2 -> verdict
        rows = ctl.ledger.rows()
        rb = [r for r in rows if r["event"] == "canary_rollback"]
        assert len(rb) == 1
        assert rb[0]["reason"] == "canary_stalled"
        assert log_next_seq(main_log) == 1    # nothing promoted
        # The rollback still repoints the canary at the base model.
        assert len(can.admin_calls("/admin/swap")) == 1
        assert ctl._canary.phase == "idle"
    finally:
        ref.close()
        can.close()


def test_promote_wave_skips_snapshots_and_assigns_fresh_seqs(tmp_path):
    canary_log = str(tmp_path / "c.jsonl")
    with DeltaLogWriter(canary_log) as w:
        w.append_snapshot("/models/base", note="base")
        w.append(_delta(0, val=0.1))
        w.append(_delta(1, val=0.2))
    main_log = str(tmp_path / "m.jsonl")
    with DeltaLogWriter(main_log) as w:
        w.append_snapshot("/models/base", note="base")
        recs = [r for r in iter_log(canary_log)]
        assert promote_wave(w, recs) == [1, 2]
    assert [r.seq for r in iter_log(main_log)] == [0, 1, 2]


# -------------------------------------------------------------- driver


def test_control_driver_is_jax_free_and_validates(tmp_path, monkeypatch):
    """The eighth driver must keep deciding while replicas recompile —
    importing it (and ticking it) must never pull jax."""
    import builtins
    import sys

    real_import = builtins.__import__

    def guard(name, *a, **kw):
        assert name != "jax", "control driver pulled jax"
        return real_import(name, *a, **kw)

    monkeypatch.setattr(builtins, "__import__", guard)
    for mod in [m for m in sys.modules if m == "jax"]:
        pass  # already-imported jax elsewhere is fine; new imports are not
    from photon_tpu.cli import control_driver

    with pytest.raises(SystemExit):
        control_driver.run(["--output-dir", str(tmp_path)])
    with pytest.raises(SystemExit):
        control_driver.run([
            "--canary", "http://127.0.0.1:1",
            "--output-dir", str(tmp_path)])   # canary without log plumbing


def test_control_driver_runs_ticks_and_writes_ledger(stub, tmp_path):
    from photon_tpu.cli import control_driver

    out = tmp_path / "ctl"
    policy = ControlPolicy(tick_s=0.01, rules=(), autoscale=None)
    ppath = tmp_path / "policy.json"
    ppath.write_text(policy.to_json())
    summary = control_driver.run([
        "--replica", stub.url,
        "--policy", str(ppath),
        "--max-ticks", "3",
        "--output-dir", str(out),
    ])
    assert summary["ticks"] == 3
    assert summary["policy_digest"] == policy.digest()
    rows = list(read_ledger(str(out / LEDGER_FILENAME)))
    events = [r["event"] for r in rows]
    assert events[0] == "controller_started"
    assert events[-1] == "controller_stopped"
    assert rows[0]["policy_digest"] == policy.digest()
    assert (out / "control-summary.json").exists()
