"""Distributed data-parallel tests on the virtual 8-device CPU mesh —
the rebuild's equivalent of the reference's Spark `local[*]` integration tier
(SURVEY.md §4): the REAL psum/shard_map/GSPMD code paths execute here.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.batch import make_dense_batch, LabeledBatch, ell_from_rows
from photon_tpu.functions.objective import GLMObjective, intercept_reg_mask
from photon_tpu.functions.problem import GLMOptimizationProblem
from photon_tpu.optim import L2RegularizationContext, OptimizerConfig, OptimizerType
from photon_tpu.parallel import (
    fit_data_parallel,
    make_mesh,
    spmd_value_and_grad,
)
from photon_tpu.ops.losses import LogisticLoss
from photon_tpu.types import TaskType


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must provide 8 CPU devices"
    return make_mesh({"data": 8})


def _make_problem():
    return GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION,
        optimizer_type=OptimizerType.LBFGS,
        optimizer_config=OptimizerConfig(max_iterations=100),
        regularization=L2RegularizationContext,
        reg_weight=0.5,
        reg_mask=intercept_reg_mask(9, 0),
    )


def _data(rng, n=320, d=8):
    x = np.concatenate([np.ones((n, 1)), rng.normal(size=(n, d))], axis=1)
    w = rng.normal(size=d + 1) * 0.5
    y = (1 / (1 + np.exp(-(x @ w))) > rng.uniform(size=n)).astype(float)
    return make_dense_batch(x, y, dtype=jnp.float64)


def test_spmd_value_and_grad_matches_local(rng, mesh):
    batch = _data(rng)
    obj = GLMObjective(loss=LogisticLoss, l2_weight=0.5,
                       reg_mask=intercept_reg_mask(9, 0))
    w = jnp.asarray(rng.normal(size=9))
    v_local, g_local = obj.value_and_grad(w, batch)
    vg = spmd_value_and_grad(obj, batch, mesh)
    v_spmd, g_spmd = vg(w)
    np.testing.assert_allclose(v_spmd, v_local, rtol=1e-10)
    np.testing.assert_allclose(g_spmd, g_local, rtol=1e-9)


def test_gspmd_fit_matches_single_device(rng, mesh):
    batch = _data(rng)
    prob = _make_problem()
    w0 = jnp.zeros(9, jnp.float64)
    model_1, res_1 = prob.run(batch, w0)
    model_8, res_8 = fit_data_parallel(prob, batch, w0, mesh)
    np.testing.assert_allclose(model_8.coefficients.means,
                               model_1.coefficients.means, atol=1e-8)
    assert int(res_8.converged_reason) == int(res_1.converged_reason)


def test_optimizer_over_spmd_objective(rng, mesh):
    """Optimizer loop outside, shard_map objective inside — collectives ride
    inside the jitted while_loop (the explicit variant of the north star)."""
    from photon_tpu.optim import LBFGS

    batch = _data(rng)
    obj = GLMObjective(loss=LogisticLoss, l2_weight=0.5,
                       reg_mask=intercept_reg_mask(9, 0))
    vg = spmd_value_and_grad(obj, batch, mesh)
    res_spmd = jax.jit(
        lambda w0: LBFGS(OptimizerConfig(max_iterations=100)).optimize(vg, w0)
    )(jnp.zeros(9, jnp.float64))
    res_local = LBFGS(OptimizerConfig(max_iterations=100)).optimize(
        obj.bind(batch), jnp.zeros(9, jnp.float64)
    )
    np.testing.assert_allclose(res_spmd.x, res_local.x, atol=1e-8)


def test_sparse_batch_data_parallel(rng, mesh):
    n, d = 160, 20
    dense = rng.normal(size=(n, d)) * (rng.uniform(size=(n, d)) < 0.25)
    rows = [(np.nonzero(dense[i])[0], dense[i][np.nonzero(dense[i])[0]])
            for i in range(n)]
    y = rng.integers(0, 2, n).astype(float)
    sb = LabeledBatch(
        features=ell_from_rows(rows, dim=d, dtype=jnp.float64),
        labels=jnp.asarray(y), offsets=jnp.zeros(n), weights=jnp.ones(n),
    )
    prob = GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION,
        regularization=L2RegularizationContext, reg_weight=0.3,
    )
    w0 = jnp.zeros(d, jnp.float64)
    m1, _ = prob.run(sb, w0)
    m8, _ = fit_data_parallel(prob, sb, w0, mesh)
    np.testing.assert_allclose(m8.coefficients.means, m1.coefficients.means,
                               atol=1e-8)


def test_uneven_rows_reject_or_pad(rng, mesh):
    # 321 rows don't divide 8; pad_rows_to_multiple zero-fills, which already
    # leaves padded rows at weight 0 — the padded fit must equal the exact one.
    from photon_tpu.parallel.mesh import pad_rows_to_multiple

    batch = _data(rng, n=321)
    padded = pad_rows_to_multiple(batch, 8)
    assert padded.n_rows == 328
    np.testing.assert_array_equal(np.asarray(padded.weights)[321:], 0.0)
    prob = _make_problem()
    m_pad, _ = fit_data_parallel(prob, padded, jnp.zeros(9, jnp.float64), mesh)
    m_ref, _ = prob.run(batch, jnp.zeros(9, jnp.float64))
    np.testing.assert_allclose(m_pad.coefficients.means,
                               m_ref.coefficients.means, atol=1e-8)


class TestMultiSliceDCN:
    """2-level dcn x ici meshes (SURVEY.md §5.8): the 8 virtual devices play
    2 slices x 4 chips; psums over ("dcn", "data") lower hierarchically on
    real multi-slice topologies and must be numerically identical to the
    single-axis path here."""

    @pytest.fixture(scope="class")
    def mesh2(self):
        from photon_tpu.parallel.mesh import make_multislice_mesh

        return make_multislice_mesh(n_slices=2, axis_sizes={"data": 4})

    def test_mesh_shape_and_axis_order(self, mesh2):
        assert mesh2.axis_names == ("dcn", "data")
        assert mesh2.shape["dcn"] == 2 and mesh2.shape["data"] == 4

    def test_spmd_value_and_grad_hierarchical(self, rng, mesh2):
        batch = _data(rng)
        obj = GLMObjective(loss=LogisticLoss, l2_weight=0.5,
                           reg_mask=intercept_reg_mask(9, 0))
        w = jnp.asarray(rng.normal(size=9))
        v_local, g_local = obj.value_and_grad(w, batch)
        vg = spmd_value_and_grad(obj, batch, mesh2, data_axis=("dcn", "data"))
        v, g = vg(w)
        np.testing.assert_allclose(v, v_local, rtol=1e-10)
        np.testing.assert_allclose(g, g_local, rtol=1e-9)

    def test_fit_matches_single_slice(self, rng, mesh2):
        batch = _data(rng)
        problem = _make_problem()
        w0 = jnp.zeros(9, jnp.float64)
        m_single, r_single = jax.jit(problem.run)(batch, w0)
        m_dcn, r_dcn = fit_data_parallel(
            problem, batch, w0, mesh2, data_axis=("dcn", "data")
        )
        np.testing.assert_allclose(
            np.asarray(m_dcn.coefficients.means),
            np.asarray(m_single.coefficients.means), atol=1e-7,
        )
        np.testing.assert_allclose(
            float(r_dcn.value), float(r_single.value), rtol=1e-9
        )

    def test_uneven_rows_padded_over_both_axes(self, rng, mesh2):
        batch = _data(rng, n=301)   # 301 % 8 != 0 -> weight-0 padding
        problem = _make_problem()
        w0 = jnp.zeros(9, jnp.float64)
        m_single, _ = jax.jit(problem.run)(batch, w0)
        m_dcn, _ = fit_data_parallel(
            problem, batch, w0, mesh2, data_axis=("dcn", "data")
        )
        np.testing.assert_allclose(
            np.asarray(m_dcn.coefficients.means),
            np.asarray(m_single.coefficients.means), atol=1e-7,
        )

    def test_model_parallel_on_dcn_mesh(self, rng):
        from photon_tpu.parallel.mesh import make_multislice_mesh
        from photon_tpu.parallel.model_parallel import fit_model_parallel

        mesh3 = make_multislice_mesh(
            n_slices=2, axis_sizes={"data": 2, "model": 2}
        )
        assert mesh3.axis_names == ("dcn", "data", "model")
        batch = _data(rng, n=320)
        problem = _make_problem()
        w0 = jnp.zeros(9, jnp.float64)
        m_single, _ = jax.jit(problem.run)(batch, w0)
        m_mp, _ = fit_model_parallel(
            problem, batch, w0, mesh3, data_axis=("dcn", "data")
        )
        np.testing.assert_allclose(
            np.asarray(m_mp.coefficients.means),
            np.asarray(m_single.coefficients.means), atol=2e-5,
        )


class TestMultiHostPrimitives:
    """parallel/distributed.py: single-process no-op semantics + the
    process-local -> global assembly primitive (SURVEY.md §5.8)."""

    def test_initialize_is_noop_single_process(self):
        from photon_tpu.parallel.distributed import initialize_distributed

        assert initialize_distributed() is False   # no coordinator spun up
        assert jax.process_count() == 1

    def test_process_file_shard(self):
        from photon_tpu.parallel.distributed import process_file_shard

        i, n = process_file_shard()
        assert (i, n) == (0, 1)

    def test_global_batch_from_local_matches_device_put(self, mesh):
        from photon_tpu.parallel.distributed import global_batch_from_local
        from photon_tpu.parallel.mesh import shard_batch_pytree

        rng = np.random.default_rng(0)
        batch = {
            "x": rng.normal(size=(64, 5)).astype(np.float32),
            "y": rng.normal(size=(64,)).astype(np.float32),
        }
        g = global_batch_from_local(batch, mesh)
        ref = shard_batch_pytree(
            {k: jnp.asarray(v) for k, v in batch.items()}, mesh
        )
        for k in batch:
            assert g[k].shape == batch[k].shape
            assert g[k].sharding == ref[k].sharding
            np.testing.assert_array_equal(np.asarray(g[k]), batch[k])

    def test_benign_init_phrases_pinned_to_installed_jax(self):
        """ADVICE r3: ensure_initialized classifies double-init as benign by
        matching exact jax error text; a jax upgrade that rewords those
        messages would silently turn a benign double-init into a hard
        failure. Pin the matched phrases against the installed jax source so
        the upgrade trips THIS test instead of breaking single-host flows."""
        import inspect

        import jax._src.distributed as jdist

        src = inspect.getsource(jdist).lower()
        # Phrases matched in photon_tpu/parallel/distributed.py (benign set).
        for phrase in ("only be called once", "must be called before"):
            assert phrase in src, (
                f"jax {jax.__version__} no longer raises {phrase!r}: update "
                "the benign-error classification in parallel/distributed.py"
            )
