"""Pipelined ingest→device data path (io/prefetch.py, data/device_cache.py,
and their threading through optim/out_of_core.py and the RE coordinate).

Contracts under test:

* the prefetch stage is a pure throughput detail — chunk order, content,
  and error behavior are bit-identical to a sequential read;
* the double-buffered device feed and the sweep cache never change a solve
  (bit-identical with/without, primed or not);
* the bf16 feed is tolerance-gated like the PR 1 dtype work: bf16 transfer
  with f32 accumulation tracks the f32 fit within documented bounds;
* chaos (``pytest -m chaos``): injected block-read ``OSError`` mid-prefetch
  recovers through ``io_retries`` (or propagates promptly without them),
  worker crashes fast-fail with in-flight chunks, and a seeded fault plan
  still yields a bit-identical bundle.
"""
import threading
import time

import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu import native
from photon_tpu.data.device_cache import DeviceSweepCache
from photon_tpu.io.prefetch import (
    device_put_chunk,
    host_feed_array,
    pipelined_puts,
    prefetch,
    read_bundle_pipelined,
)

requires_native = pytest.mark.skipif(
    native.get_lib() is None, reason="native decoder unavailable"
)


# ---------------------------------------------------------------------------
# prefetch stage (no IO needed)


def test_prefetch_preserves_order_and_items():
    items = list(range(57))
    assert list(prefetch(iter(items), depth=3)) == items
    assert list(prefetch(iter(items), depth=0)) == items  # disabled path


def test_prefetch_bounded_queue_backpressure():
    """The producer must never run more than ``depth`` + in-flight items
    ahead of the consumer."""
    produced = []

    def gen():
        for i in range(50):
            produced.append(i)
            yield i

    it = prefetch(gen(), depth=2)
    first = next(it)
    time.sleep(0.2)  # give the producer every chance to overrun
    assert first == 0
    # consumed 1; queue holds <= 2; one more may be blocked in put.
    assert len(produced) <= 1 + 2 + 2
    assert list(it) == list(range(1, 50))


def test_prefetch_propagates_producer_error_in_order():
    def gen():
        yield 1
        yield 2
        raise OSError("stream died")

    it = prefetch(gen(), depth=4)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(OSError, match="stream died"):
        next(it)


def test_prefetch_abandoned_consumer_stops_producer():
    state = {"n": 0}

    def gen():
        while True:
            state["n"] += 1
            yield state["n"]

    it = prefetch(gen(), depth=2)
    assert next(it) == 1
    it.close()  # GeneratorExit → stop flag → producer unblocks and exits
    n_after_close = state["n"]
    time.sleep(0.2)
    assert state["n"] == n_after_close
    assert threading.active_count() < 50  # no thread leak across tests


def test_pipelined_puts_keeps_one_in_flight():
    calls = []

    def put(x):
        calls.append(x)
        return x * 10

    out = []
    for y in pipelined_puts(iter(range(5)), put, ahead=1):
        # When item N is yielded, item N+1's put has already been issued.
        out.append((y, len(calls)))
    assert [y for y, _ in out] == [0, 10, 20, 30, 40]
    assert [c for _, c in out] == [2, 3, 4, 5, 5]


# ---------------------------------------------------------------------------
# bf16 feed


def test_host_feed_array_bf16_halves_bytes():
    a = np.linspace(0, 1, 64, dtype=np.float32)
    b = host_feed_array(a, "bfloat16")
    assert b.nbytes == a.nbytes // 2
    assert host_feed_array(a, None) is a
    # one-hot / small-integer values are EXACT in bf16
    ones = np.ones(16, np.float32)
    np.testing.assert_array_equal(
        host_feed_array(ones, "bfloat16").astype(np.float32), ones
    )


def test_bf16_feed_matvec_accumulates_f32():
    from photon_tpu.data.batch import SparseFeatures

    rng = np.random.default_rng(0)
    idx = rng.integers(0, 40, size=(32, 6)).astype(np.int32)
    val = rng.normal(size=(32, 6)).astype(np.float32)
    w = jnp.asarray(rng.normal(size=40).astype(np.float32))
    f32 = SparseFeatures(jnp.asarray(idx), jnp.asarray(val), 40)
    b16 = SparseFeatures(
        jnp.asarray(idx), jnp.asarray(host_feed_array(val, "bfloat16")), 40
    )
    out = b16.matvec(w)
    assert out.dtype == jnp.float32  # promotion: bf16 storage, f32 math
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(f32.matvec(w)), rtol=2e-2, atol=2e-2
    )


def test_bf16_feed_fit_tolerance_gate():
    """The PR 1-style dtype gate for the feed: a fixed-effect fit on a
    bf16-fed bundle must track the f32 fit to documented tolerance."""
    from photon_tpu.data.batch import LabeledBatch, SparseFeatures
    from tests.test_out_of_core import _data, _problem

    idx, val, labels = _data(n=600, dim=120, seed=7)
    problem = _problem(max_iter=60)

    def fit(v):
        batch = LabeledBatch(
            features=SparseFeatures(jnp.asarray(idx), jnp.asarray(v), 150),
            labels=jnp.asarray(labels),
            offsets=jnp.zeros((len(labels),), jnp.float32),
            weights=jnp.ones((len(labels),), jnp.float32),
        )
        m, r = problem.run(batch, jnp.zeros((150,), jnp.float32))
        return np.asarray(m.coefficients.means), float(r.value)

    w32, f32 = fit(val)
    w16, f16 = fit(host_feed_array(val, "bfloat16"))
    assert f16 == pytest.approx(f32, rel=5e-3)
    np.testing.assert_allclose(w16, w32, rtol=0.0, atol=5e-2)


def test_bf16_bundle_re_dataset_repacks_f32():
    """A bf16-fed bundle must still produce f32 RE buckets: the feed narrows
    TRANSFER only — per-entity Newton solves (batched Cholesky) have no bf16
    lowering and accumulate in f32 over the already-quantized values."""
    import dataclasses

    from photon_tpu.estimators.config import RandomEffectDataConfig
    from photon_tpu.estimators.game_estimator import (
        build_re_dataset_from_bundle,
    )
    from tests.test_checkpoint import _bundle

    b = _bundle()
    sf = b.features["g"]
    b16 = dataclasses.replace(b, features={
        "g": dataclasses.replace(sf, val=sf.val.astype(jnp.bfloat16)),
    })
    ds = build_re_dataset_from_bundle(
        b16, RandomEffectDataConfig(re_type="userId", feature_shard="g"),
    )
    assert ds.buckets[0].val.dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(ds.buckets[0].val),
        np.asarray(build_re_dataset_from_bundle(
            b, RandomEffectDataConfig(re_type="userId", feature_shard="g"),
        ).buckets[0].val),
        rtol=1e-2, atol=1e-2,
    )


# ---------------------------------------------------------------------------
# device sweep cache


def _cache_put(cache, key, a):
    """Pin one host array through the production surface (get_or_put is
    what the OOC feed calls)."""
    return cache.get_or_put(key, a.nbytes, lambda: jnp.asarray(a), retain=a)


def test_sweep_cache_hit_miss_and_budget_spill():
    cache = DeviceSweepCache(budget_bytes=1000)
    a = np.ones(100, np.float32)          # 400 B: fits
    big = np.ones(1000, np.float32)       # 4 KB: spills

    d1 = _cache_put(cache, ("a",), a)
    d2 = _cache_put(cache, ("a",), a)
    assert d1 is d2                        # hit returns the pinned array
    assert cache.stats()["entries"] == 1
    s1 = _cache_put(cache, ("big",), big)
    s2 = _cache_put(cache, ("big",), big)
    assert s1 is not s2                    # spill: rebuilt per use
    assert cache.spilled_bytes >= big.nbytes
    np.testing.assert_array_equal(np.asarray(s1), big)
    cache.release()
    assert cache.stats()["entries"] == 0 and cache.resident_bytes == 0


def test_sweep_cache_spill_counted_once_per_key():
    """Spilled bytes must read DATASET size, not dataset × passes: a
    multi-pass solve re-missing the same busted-budget chunk every pass
    may not re-add its bytes (the figure drives --sweep-cache-mb sizing)."""
    cache = DeviceSweepCache(budget_bytes=100)
    big = np.ones(1000, np.float32)
    for _ in range(5):
        _cache_put(cache, ("big",), big)
    assert cache.spilled_bytes == big.nbytes
    cache.release()
    assert cache.spilled_bytes == 0


def test_sweep_cache_discard_rolls_back_accounting():
    """discard() frees a pin whose host referent was replaced (the primer's
    regrow cleanup) and rolls the byte/entry accounting back."""
    cache = DeviceSweepCache(budget_bytes=10_000)
    a = np.ones(100, np.float32)
    _cache_put(cache, ("a",), a)
    assert cache.stats() == {"budget_bytes": 10_000, "resident_bytes": 400,
                             "spilled_bytes": 0, "entries": 1}
    cache.discard(("a",))
    cache.discard(("missing",))            # unknown keys are a no-op
    assert cache.stats()["entries"] == 0 and cache.resident_bytes == 0
    cache.release()


def test_sweep_cache_spilled_mirror_lookups_count_as_misses():
    """A budget-busted RE dataset re-uploads every sweep — later lookups
    must NOT report cache hits (a 'healthy hit rate' over a spilled
    dataset would hide exactly the regression the cache exists to kill)."""
    from photon_tpu.data.random_effect import build_random_effect_dataset
    from photon_tpu.obs.metrics import REGISTRY

    rng = np.random.default_rng(2)
    n, k, dim = 40, 3, 20
    ds = build_random_effect_dataset(
        re_type="userId",
        entity_keys_per_row=np.array([f"u{i % 4}" for i in range(n)], object),
        idx=rng.integers(0, dim, size=(n, k)).astype(np.int32),
        val=rng.normal(size=(n, k)).astype(np.float32),
        labels=(rng.random(n) < 0.5).astype(np.float32),
        global_dim=dim,
        host_resident=True,
    )
    tiny = DeviceSweepCache(budget_bytes=8)
    hits = REGISTRY.counter("sweep_cache_hits_total")
    h0 = sum(v for _, v in hits.collect())
    assert tiny.dataset_mirror(ds) is ds
    assert tiny.dataset_mirror(ds) is ds
    assert tiny.dataset_mirror(ds) is ds
    assert sum(v for _, v in hits.collect()) == h0
    tiny.release()


def test_sweep_cache_disabled_budget_zero():
    cache = DeviceSweepCache(budget_bytes=0)
    assert not cache.enabled
    a = np.ones(10, np.float32)
    out = _cache_put(cache, ("k",), a)
    assert cache.stats()["entries"] == 0
    np.testing.assert_array_equal(np.asarray(out), a)


def test_sweep_cache_dataset_mirror_identity_stable():
    from photon_tpu.data.random_effect import build_random_effect_dataset

    rng = np.random.default_rng(1)
    n, k, dim = 60, 4, 30
    ds = build_random_effect_dataset(
        re_type="userId",
        entity_keys_per_row=np.array([f"u{i % 6}" for i in range(n)], object),
        idx=rng.integers(0, dim, size=(n, k)).astype(np.int32),
        val=rng.normal(size=(n, k)).astype(np.float32),
        labels=(rng.random(n) < 0.5).astype(np.float32),
        global_dim=dim,
        host_resident=True,
    )
    assert isinstance(ds.buckets[0].idx, np.ndarray)  # host build
    cache = DeviceSweepCache()
    m1 = cache.dataset_mirror(ds)
    m2 = cache.dataset_mirror(ds)
    assert m1 is m2                       # identity stable across sweeps
    assert not isinstance(m1.buckets[0].idx, np.ndarray)
    for b_host, b_dev in zip(ds.buckets, m1.buckets):
        np.testing.assert_array_equal(b_host.proj, np.asarray(b_dev.proj))
    # Budget-busted datasets keep the ORIGINAL object (streaming fallback).
    tiny = DeviceSweepCache(budget_bytes=8)
    assert tiny.dataset_mirror(ds) is ds
    assert tiny.dataset_mirror(ds) is ds  # and stays stable
    cache.release()


def test_re_fit_with_sweep_cache_matches_without():
    """A multi-sweep GAME fit over a host-resident RE dataset must be
    bit-identical with the sweep cache on vs off (the cache is a transfer
    detail, not a semantics change) — and the cached fit must actually HIT
    the cache on sweep 1."""
    from tests.test_checkpoint import _bundle, _final_arrays
    from photon_tpu.estimators.config import (
        FixedEffectDataConfig,
        GLMOptimizationConfiguration,
        RandomEffectDataConfig,
    )
    from photon_tpu.estimators.game_estimator import GameEstimator
    from photon_tpu.obs.metrics import REGISTRY
    from photon_tpu.optim import RegularizationContext, RegularizationType
    from photon_tpu.types import TaskType

    bundle = _bundle()
    base = dict(
        regularization=RegularizationContext(RegularizationType.L2),
        max_iterations=10,
    )
    configs = [{
        "fixed": GLMOptimizationConfiguration(reg_weight=1.0, **base),
        "perUser": GLMOptimizationConfiguration(reg_weight=1.0, **base),
    }]

    def fit(cache_mb):
        est = GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_data_configs={
                "fixed": FixedEffectDataConfig("g"),
                "perUser": RandomEffectDataConfig(
                    re_type="userId", feature_shard="g",
                    host_resident=True),
            },
            n_sweeps=2,
            sweep_cache_mb=cache_mb,
        )
        return est.fit(bundle, None, configs)

    hits = REGISTRY.counter("sweep_cache_hits_total")
    h0 = sum(v for _, v in hits.collect())
    with_cache = fit(cache_mb=None)
    assert sum(v for _, v in hits.collect()) > h0   # sweep 1 hit the mirror
    without = fit(cache_mb=0)
    for a, b in zip(_final_arrays(with_cache), _final_arrays(without)):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# out-of-core: cache + primed init


def _ooc_fixture(seed=0):
    from tests.test_out_of_core import _data, _problem

    idx, val, labels = _data(n=700, dim=150, seed=seed)
    return idx, val, labels, _problem(max_iter=25)


def test_ooc_device_cache_solve_bit_identical():
    from photon_tpu.optim.out_of_core import ChunkedGLMData, run_out_of_core

    idx, val, labels, problem = _ooc_fixture()
    data = ChunkedGLMData.from_arrays(idx, val, labels, 150, chunk_rows=256)
    m0, r0 = run_out_of_core(problem, data)
    cache = DeviceSweepCache()
    data2 = ChunkedGLMData.from_arrays(idx, val, labels, 150, chunk_rows=256)
    m1, r1 = run_out_of_core(problem, data2, device_cache=cache)
    assert cache.stats()["entries"] == data2.n_chunks
    cache.release()
    np.testing.assert_array_equal(np.asarray(m0.coefficients.means),
                                  np.asarray(m1.coefficients.means))
    assert float(r0.value) == float(r1.value)


def test_ooc_primed_init_bit_identical():
    """StreamPrimer's overlapped init pass must reproduce the unprimed
    solve exactly (same kernels, same accumulation order)."""
    from photon_tpu.ops.losses import loss_for_task
    from photon_tpu.optim.out_of_core import (
        ChunkedGLMData,
        StreamPrimer,
        run_out_of_core,
    )
    from photon_tpu.types import TaskType

    idx, val, labels, problem = _ooc_fixture(seed=4)

    def stream():
        from photon_tpu.data.batch import SparseFeatures

        class Chunk:
            def __init__(self, lo, hi):
                self.features = {"g": SparseFeatures(
                    idx=idx[lo:hi], val=val[lo:hi], dim=150)}
                self.labels = labels[lo:hi]
                self.offsets = np.zeros(hi - lo, np.float32)
                self.weights = np.ones(hi - lo, np.float32)
                self.n_rows = hi - lo

        for lo in range(0, 700, 210):
            yield Chunk(lo, min(lo + 210, 700))

    data_plain = ChunkedGLMData.from_stream(stream(), "g", 150,
                                            chunk_rows=256)
    m0, r0 = run_out_of_core(problem, data_plain)

    loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    primer = StreamPrimer(loss, 150)
    data = ChunkedGLMData.from_stream(
        prefetch(stream(), depth=2), "g", 150, chunk_rows=256,
        on_chunk=primer,
    )
    m1, r1 = run_out_of_core(problem, data, primed=primer.primed())
    np.testing.assert_array_equal(np.asarray(m0.coefficients.means),
                                  np.asarray(m1.coefficients.means))
    assert float(r0.value) == float(r1.value)
    assert int(r0.iterations) == int(r1.iterations)


def test_ooc_primer_discards_pins_orphaned_by_regrow():
    """A mid-stream ELL width regrow replaces already-flushed chunk arrays;
    the primer must discard its now-unreachable cache pins (budget holds
    live data, not orphans) and the primed solve still matches unprimed."""
    from photon_tpu.ops.losses import loss_for_task
    from photon_tpu.optim.out_of_core import (
        ChunkedGLMData,
        StreamPrimer,
        run_out_of_core,
    )
    from photon_tpu.types import TaskType

    rng = np.random.default_rng(9)
    dim, n = 80, 600

    def stream():
        from photon_tpu.data.batch import SparseFeatures

        class Chunk:
            def __init__(self, lo, hi, k):
                idx = rng.integers(0, dim, size=(hi - lo, k)).astype(np.int32)
                val = (rng.normal(size=(hi - lo, k)) / np.sqrt(k)).astype(
                    np.float32)
                self.features = {"g": SparseFeatures(idx=idx, val=val,
                                                     dim=dim)}
                self.labels = (rng.random(hi - lo) < 0.5).astype(np.float32)
                self.offsets = np.zeros(hi - lo, np.float32)
                self.weights = np.ones(hi - lo, np.float32)
                self.n_rows = hi - lo

        yield Chunk(0, 300, k=4)       # narrow first...
        yield Chunk(300, 600, k=9)     # ...then wider: regrow fires

    rng_state = rng.bit_generator.state
    loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    cache = DeviceSweepCache()
    primer = StreamPrimer(loss, dim, device_cache=cache)
    data = ChunkedGLMData.from_stream(stream(), "g", dim, chunk_rows=256,
                                      on_chunk=primer)
    # Every RESIDENT entry must key a live chunk array: the regrown chunk
    # 0/1 pins were discarded, and the budget reflects only reachable data.
    live_ids = {id(c.idx) for c in data.chunks}
    resident_keys = {k[1] for k in cache._entries}
    assert resident_keys <= live_ids
    from tests.test_out_of_core import _problem

    m1, r1 = run_out_of_core(_problem(max_iter=25), data,
                             device_cache=cache, primed=primer.primed())
    cache.release()
    rng.bit_generator.state = rng_state
    data2 = ChunkedGLMData.from_stream(stream(), "g", dim, chunk_rows=256)
    m2, r2 = run_out_of_core(_problem(max_iter=25), data2)
    np.testing.assert_array_equal(np.asarray(m1.coefficients.means),
                                  np.asarray(m2.coefficients.means))
    assert float(r1.value) == float(r2.value)


def test_ooc_primed_rejected_on_mismatched_start():
    """A prime computed at a different w0 must be IGNORED, not trusted —
    the solve falls back to fresh init passes and still converges right."""
    from photon_tpu.ops.losses import loss_for_task
    from photon_tpu.optim.out_of_core import (
        ChunkedGLMData,
        OutOfCoreLBFGS,
        StreamPrimer,
    )
    from photon_tpu.types import TaskType

    idx, val, labels, problem = _ooc_fixture(seed=5)
    data = ChunkedGLMData.from_arrays(idx, val, labels, 150, chunk_rows=256)
    loss = loss_for_task(TaskType.LOGISTIC_REGRESSION)
    primer = StreamPrimer(loss, 150, w0=jnp.ones((150,), jnp.float32))
    for i, c in enumerate(data.chunks):
        primer(i, c, data.labels[i], data.offsets[i], data.weights[i])
    solver = OutOfCoreLBFGS(loss=loss, l2_weight=1.0,
                            config=problem.optimizer_config)
    r_primed = solver.optimize(data, jnp.zeros((150,), jnp.float32),
                               primed=primer.primed())
    r_fresh = solver.optimize(data, jnp.zeros((150,), jnp.float32))
    assert float(r_primed.value) == float(r_fresh.value)


def test_ooc_bf16_value_dtype_tolerance():
    """bf16-fed out-of-core solve (value_dtype=bfloat16: bf16 transfer,
    f32 accumulation) tracks the f32 solve within the documented gate."""
    from photon_tpu.optim.out_of_core import ChunkedGLMData, run_out_of_core

    idx, val, labels, problem = _ooc_fixture(seed=6)
    d32 = ChunkedGLMData.from_arrays(idx, val, labels, 150, chunk_rows=256)
    m32, r32 = run_out_of_core(problem, d32)
    d16 = ChunkedGLMData.from_arrays(idx, val, labels, 150, chunk_rows=256,
                                     value_dtype=jnp.bfloat16)
    assert d16.chunks[0].val.dtype == jnp.bfloat16
    assert d16.streamed_bytes_per_pass() < d32.streamed_bytes_per_pass()
    m16, r16 = run_out_of_core(problem, d16)
    assert float(r16.value) == pytest.approx(float(r32.value), rel=1e-2)
    np.testing.assert_allclose(np.asarray(m16.coefficients.means),
                               np.asarray(m32.coefficients.means),
                               rtol=0.0, atol=6e-2)


# ---------------------------------------------------------------------------
# end-to-end pipelined reads (native decoder)


def _write_stream_file(tmp_path, n=400, name="d.avro", block_records=64):
    from photon_tpu.io.avro import write_container
    from tests.test_streaming import SCHEMA, _index, _make_records

    rng = np.random.default_rng(0)
    feat_names, records = _make_records(rng, n=n)
    path = str(tmp_path / name)
    write_container(path, SCHEMA, records, block_records=block_records)
    return path, _index(feat_names)


@requires_native
def test_read_bundle_pipelined_bit_identical(tmp_path):
    from photon_tpu.io.data_reader import InputColumnNames
    from photon_tpu.io.streaming import StreamingAvroReader

    path, imap = _write_stream_file(tmp_path)
    cols = InputColumnNames(response="label")
    seq = StreamingAvroReader({"g": imap}, columns=cols,
                              id_tag_columns=("userId",)).read(path)
    pipe = read_bundle_pipelined(
        {"g": imap}, None, cols, ("userId",), path,
        capture_uids=True, depth=3,
    )
    np.testing.assert_array_equal(seq.labels, pipe.labels)
    np.testing.assert_array_equal(seq.uids, pipe.uids)
    np.testing.assert_array_equal(seq.id_tags["userId"],
                                  pipe.id_tags["userId"])
    np.testing.assert_array_equal(np.asarray(seq.features["g"].idx),
                                  np.asarray(pipe.features["g"].idx))
    np.testing.assert_array_equal(np.asarray(seq.features["g"].val),
                                  np.asarray(pipe.features["g"].val))


@requires_native
def test_read_bundle_pipelined_bf16_feed(tmp_path):
    from photon_tpu.io.data_reader import InputColumnNames

    path, imap = _write_stream_file(tmp_path, n=120)
    cols = InputColumnNames(response="label")
    b = read_bundle_pipelined(
        {"g": imap}, None, cols, (), path, capture_uids=False,
        feed_dtype="bfloat16",
    )
    assert b.features["g"].val.dtype == jnp.bfloat16
    assert b.features["g"].idx.dtype == jnp.int32  # indices stay exact


@requires_native
def test_device_put_chunk_moves_numeric_payload(tmp_path):
    from photon_tpu.io.data_reader import InputColumnNames
    from photon_tpu.io.streaming import StreamingAvroReader

    path, imap = _write_stream_file(tmp_path, n=100)
    cols = InputColumnNames(response="label")
    sr = StreamingAvroReader({"g": imap}, columns=cols)
    (chunk,) = list(sr.iter_chunks(path))
    dev = device_put_chunk(chunk, feed_dtype="bfloat16")
    assert dev.n_rows == chunk.n_rows
    assert not isinstance(dev.features["g"].idx, np.ndarray)
    assert dev.features["g"].val.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(dev.features["g"].val, np.float32),
        np.asarray(chunk.features["g"].val),
        rtol=1e-2, atol=1e-2,
    )


@requires_native
def test_uid_dictionary_growth_warns_once(tmp_path, caplog, monkeypatch):
    import logging

    from photon_tpu.io.data_reader import InputColumnNames
    from photon_tpu.io.streaming import StreamingAvroReader

    path, imap = _write_stream_file(tmp_path, n=300, block_records=32)
    monkeypatch.setenv("PHOTON_UID_WARN_ROWS", "100")
    cols = InputColumnNames(response="label")
    sr = StreamingAvroReader({"g": imap}, columns=cols, capture_uids=True,
                             chunk_rows=64)
    with caplog.at_level(logging.WARNING, logger="photon_tpu.io"):
        n = sum(c.n_rows for c in sr.iter_chunks(path))
    assert n == 300
    warns = [r for r in caplog.records if "uid dictionary" in r.message]
    assert len(warns) == 1                 # one-time, not per chunk
    assert "unique entries" in warns[0].getMessage()

    # capture_uids=False flows never warn.
    caplog.clear()
    sr2 = StreamingAvroReader({"g": imap}, columns=cols, capture_uids=False,
                              chunk_rows=64)
    with caplog.at_level(logging.WARNING, logger="photon_tpu.io"):
        list(sr2.iter_chunks(path))
    assert not [r for r in caplog.records if "uid dictionary" in r.message]


# ---------------------------------------------------------------------------
# chaos (pytest -m chaos; slow keeps these out of the tier-1 budget)


@requires_native
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_block_read_oserror_mid_prefetch_recovers(tmp_path):
    """An injected transient block-read OSError fires INSIDE the prefetch
    producer thread; io_retries reopens and the prefetched bundle is
    bit-identical to a fault-free sequential read."""
    from photon_tpu.faults import FaultPlan, FaultSpec, active_plan
    from photon_tpu.io.data_reader import InputColumnNames
    from photon_tpu.io.streaming import StreamingAvroReader

    path, imap = _write_stream_file(tmp_path, n=400, block_records=32)
    cols = InputColumnNames(response="label")
    ref = StreamingAvroReader({"g": imap}, columns=cols).read(path)

    plan = FaultPlan(seed=7, specs=[
        FaultSpec(site="io.block_read", error="os", after=3, count=2),
    ])
    with active_plan(plan) as inj:
        pipe = read_bundle_pipelined(
            {"g": imap}, None, cols, (), path, capture_uids=True, depth=2,
        )
    assert inj.fired("io.block_read") == 2   # the faults really happened
    np.testing.assert_array_equal(ref.labels, pipe.labels)
    np.testing.assert_array_equal(np.asarray(ref.features["g"].idx),
                                  np.asarray(pipe.features["g"].idx))
    np.testing.assert_array_equal(np.asarray(ref.features["g"].val),
                                  np.asarray(pipe.features["g"].val))


@requires_native
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_block_read_oserror_without_retries_fails_fast(tmp_path):
    """With io_retries=0 the same fault must PROPAGATE through the prefetch
    thread to the consumer (promptly — no hang, no silent truncation)."""
    from photon_tpu.faults import FaultPlan, FaultSpec, active_plan
    from photon_tpu.io.data_reader import InputColumnNames
    from photon_tpu.io.streaming import StreamingAvroReader

    path, imap = _write_stream_file(tmp_path, n=400, block_records=32)
    cols = InputColumnNames(response="label")
    sr = StreamingAvroReader({"g": imap}, columns=cols, io_retries=0)
    plan = FaultPlan(seed=7, specs=[
        FaultSpec(site="io.block_read", error="os", after=3, count=1),
    ])
    t0 = time.monotonic()
    with active_plan(plan):
        with pytest.raises(OSError):
            list(prefetch(sr.iter_chunks(path), depth=2))
    assert time.monotonic() - t0 < 30.0      # fast-fail, not a hang


@requires_native
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_prefetch_fault_point_fires(tmp_path):
    """The producer loop's own fault point (io.prefetch) kills the stage
    mid-stream and the error reaches the consumer."""
    from photon_tpu.faults import FaultPlan, FaultSpec, active_plan
    from photon_tpu.io.data_reader import InputColumnNames
    from photon_tpu.io.streaming import StreamingAvroReader

    path, imap = _write_stream_file(tmp_path, n=400, block_records=32)
    cols = InputColumnNames(response="label")
    sr = StreamingAvroReader({"g": imap}, columns=cols, chunk_rows=64)
    plan = FaultPlan(seed=0, specs=[
        FaultSpec(site="io.prefetch", error="runtime", after=2, count=1),
    ])
    with active_plan(plan) as inj:
        with pytest.raises(RuntimeError):
            list(prefetch(sr.iter_chunks(path), depth=2))
    assert inj.fired("io.prefetch") == 1


@requires_native
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_worker_crash_fast_fails_with_inflight_chunks(tmp_path):
    """A corrupt second file kills its decode worker mid-pool; the parallel
    chunk stream must surface the failure promptly even though file 1's
    chunks are already in flight through the prefetcher."""
    from photon_tpu.io.avro import SchemaError
    from photon_tpu.io.data_reader import FeatureShardConfig, InputColumnNames
    from photon_tpu.io.parallel_ingest import iter_chunks_parallel

    p1, imap = _write_stream_file(tmp_path, n=200, name="a.avro",
                                  block_records=32)
    bad = tmp_path / "b.avro"
    data = bytearray((tmp_path / "a.avro").read_bytes())
    data[len(data) // 2:] = b"\xff" * (len(data) - len(data) // 2)
    bad.write_bytes(bytes(data))

    cols = InputColumnNames(response="label")
    t0 = time.monotonic()
    with pytest.raises((SchemaError, ValueError, OSError)):
        list(prefetch(iter_chunks_parallel(
            [p1, str(bad)], {"g": imap}, {"g": FeatureShardConfig()},
            cols, (), n_workers=2, chunk_rows=64,
        ), depth=2))
    assert time.monotonic() - t0 < 60.0


@requires_native
@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_seeded_plan_bundle_bit_identical_vs_sequential(tmp_path):
    """Under one seeded fault plan (delays + one recovered OSError), the
    prefetched multi-file read still equals the sequential read bit for
    bit — fault recovery may cost time, never rows."""
    from photon_tpu.faults import FaultPlan, FaultSpec, active_plan
    from photon_tpu.io.avro import write_container
    from photon_tpu.io.data_reader import InputColumnNames
    from photon_tpu.io.streaming import StreamingAvroReader
    from tests.test_streaming import SCHEMA, _index, _make_records

    rng = np.random.default_rng(3)
    feat_names, records = _make_records(rng, n=500)
    p1, p2 = str(tmp_path / "s1.avro"), str(tmp_path / "s2.avro")
    write_container(p1, SCHEMA, records[:250], block_records=32)
    write_container(p2, SCHEMA, records[250:], block_records=32)
    imap = _index(feat_names)
    cols = InputColumnNames(response="label")

    ref = StreamingAvroReader({"g": imap}, columns=cols,
                              id_tag_columns=("userId",)).read([p1, p2])
    plan = FaultPlan(seed=11, specs=[
        FaultSpec(site="io.block_read", delay_s=0.002, every=5),
        FaultSpec(site="io.block_read", error="os", after=9, count=1),
        FaultSpec(site="io.prefetch", delay_s=0.001, every=2),
    ])
    with active_plan(plan) as inj:
        pipe = read_bundle_pipelined(
            {"g": imap}, None, cols, ("userId",), [p1, p2],
            capture_uids=True, depth=2,
        )
    assert inj.fired("io.block_read") >= 2
    assert inj.fired("io.prefetch") >= 1
    np.testing.assert_array_equal(ref.labels, pipe.labels)
    np.testing.assert_array_equal(ref.uids, pipe.uids)
    np.testing.assert_array_equal(ref.id_tags["userId"],
                                  pipe.id_tags["userId"])
    np.testing.assert_array_equal(np.asarray(ref.features["g"].idx),
                                  np.asarray(pipe.features["g"].idx))
    np.testing.assert_array_equal(np.asarray(ref.features["g"].val),
                                  np.asarray(pipe.features["g"].val))
