"""Optimizer golden tests: closed-form quadratics, scipy/sklearn parity,
cross-optimizer agreement (TRON vs L-BFGS), as in the reference's
numerical-parity tier (SURVEY.md §4).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize

from photon_tpu.optim import (
    GRADIENT_CONVERGED,
    LBFGS,
    OWLQN,
    TRON,
    OptimizerConfig,
)
from photon_tpu.ops.losses import LogisticLoss


def quadratic_problem(rng, d=8, cond=10.0):
    q, _ = np.linalg.qr(rng.normal(size=(d, d)))
    eigs = np.linspace(1.0, cond, d)
    a = (q * eigs) @ q.T
    b = rng.normal(size=d)
    a = jnp.asarray(a, jnp.float32)
    b = jnp.asarray(b, jnp.float32)

    def vg(x):
        g = a @ x - b
        return 0.5 * x @ a @ x - b @ x, g

    x_star = jnp.linalg.solve(a, b)
    return vg, x_star


def logistic_data(rng, n=200, d=10):
    x = rng.normal(size=(n, d)).astype(np.float32)
    w_true = rng.normal(size=d).astype(np.float32)
    p = 1.0 / (1.0 + np.exp(-(x @ w_true)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


def logistic_objective(x, y, l2=0.1):
    def value(w):
        z = x @ w
        return jnp.sum(LogisticLoss.loss(z, y)) + 0.5 * l2 * jnp.sum(w * w)

    return jax.value_and_grad(value), value


class TestLBFGS:
    def test_quadratic_exact(self, rng):
        vg, x_star = quadratic_problem(rng)
        res = jax.jit(lambda x0: LBFGS(OptimizerConfig()).optimize(vg, x0))(
            jnp.zeros(8, jnp.float32)
        )
        np.testing.assert_allclose(res.x, x_star, atol=1e-4)
        assert int(res.converged_reason) in (2, 3)

    def test_logistic_vs_scipy(self, rng):
        x, y = logistic_data(rng)
        vg, value = logistic_objective(x, y)
        res = jax.jit(
            lambda w0: LBFGS(OptimizerConfig(max_iterations=200)).optimize(vg, w0)
        )(jnp.zeros(10, jnp.float32))
        ref = scipy.optimize.minimize(
            lambda w: float(value(jnp.asarray(w, jnp.float32))),
            np.zeros(10),
            jac=lambda w: np.asarray(vg(jnp.asarray(w, jnp.float32))[1], np.float64),
            method="L-BFGS-B",
        )
        np.testing.assert_allclose(float(res.value), ref.fun, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(res.x), ref.x, atol=2e-3)

    def test_tracker_is_monotone(self, rng):
        x, y = logistic_data(rng)
        vg, _ = logistic_objective(x, y)
        res = LBFGS(OptimizerConfig()).optimize(vg, jnp.zeros(10, jnp.float32))
        vals = np.asarray(res.values)[: int(res.iterations) + 1]
        assert np.all(np.diff(vals) <= 1e-5)

    def test_vmap_batched_solves(self, rng):
        # The random-effect path: one optimizer, many independent problems.
        n_ent, n, d = 5, 40, 4
        xs = jnp.asarray(rng.normal(size=(n_ent, n, d)).astype(np.float32))
        ws = jnp.asarray(rng.normal(size=(n_ent, d)).astype(np.float32))
        ys = (jax.nn.sigmoid(jnp.einsum("end,ed->en", xs, ws)) > 0.5).astype(jnp.float32)

        def solve(x, y):
            def value(w):
                return jnp.sum(LogisticLoss.loss(x @ w, y)) + 0.05 * jnp.sum(w * w)

            return LBFGS(OptimizerConfig(max_iterations=50)).optimize(
                jax.value_and_grad(value), jnp.zeros(d, jnp.float32)
            )

        res = jax.jit(jax.vmap(solve))(xs, ys)
        assert res.x.shape == (n_ent, d)
        # Each batched solve must match its standalone solve.
        single = solve(xs[1], ys[1])
        np.testing.assert_allclose(res.x[1], single.x, atol=1e-4)


class TestOWLQN:
    def test_l1_matches_sklearn(self, rng):
        from sklearn.linear_model import LogisticRegression

        x, y = logistic_data(rng, n=300, d=8)
        l1 = 2.0
        vg = jax.value_and_grad(
            lambda w: jnp.sum(LogisticLoss.loss(x @ w, y))
        )
        res = jax.jit(
            lambda w0: OWLQN(OptimizerConfig(max_iterations=300)).optimize(
                vg, w0, jnp.full((8,), l1)
            )
        )(jnp.zeros(8, jnp.float32))
        ref = LogisticRegression(
            penalty="l1", C=1.0 / l1, solver="liblinear", fit_intercept=False,
            tol=1e-8, max_iter=2000,
        ).fit(np.asarray(x), np.asarray(y))

        def total(w):
            z = np.asarray(x) @ w
            return float(
                np.sum(np.maximum(z, 0) - np.asarray(y) * z + np.log1p(np.exp(-np.abs(z))))
                + l1 * np.abs(w).sum()
            )

        # Objective parity within 0.5% (different solvers, same optimum).
        assert float(res.value) <= total(ref.coef_[0]) * 1.005
        # Sparsity: OWL-QN must produce exact zeros where sklearn does.
        got_zero = np.asarray(res.x) == 0.0
        assert got_zero.sum() >= (np.abs(ref.coef_[0]) < 1e-6).sum() - 1

    def test_reduces_to_lbfgs_when_no_l1(self, rng):
        x, y = logistic_data(rng)
        vg, _ = logistic_objective(x, y)
        a = OWLQN(OptimizerConfig(max_iterations=150)).optimize(
            vg, jnp.zeros(10, jnp.float32), jnp.zeros((10,))
        )
        b = LBFGS(OptimizerConfig(max_iterations=150)).optimize(
            vg, jnp.zeros(10, jnp.float32)
        )
        np.testing.assert_allclose(float(a.value), float(b.value), rtol=1e-4)


class TestTRON:
    def _hvp(self, vg):
        grad_fn = lambda w: vg(w)[1]
        return lambda w: (lambda v: jax.jvp(grad_fn, (w,), (v,))[1])

    def test_quadratic_exact(self, rng):
        vg, x_star = quadratic_problem(rng)
        res = jax.jit(
            lambda x0: TRON(OptimizerConfig()).optimize(vg, x0, self._hvp(vg))
        )(jnp.zeros(8, jnp.float32))
        np.testing.assert_allclose(res.x, x_star, atol=1e-3)

    def test_agrees_with_lbfgs_on_logistic(self, rng):
        x, y = logistic_data(rng)
        vg, _ = logistic_objective(x, y)
        a = jax.jit(
            lambda w0: TRON(OptimizerConfig(max_iterations=100)).optimize(
                vg, w0, self._hvp(vg)
            )
        )(jnp.zeros(10, jnp.float32))
        b = LBFGS(OptimizerConfig(max_iterations=200)).optimize(
            vg, jnp.zeros(10, jnp.float32)
        )
        np.testing.assert_allclose(float(a.value), float(b.value), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(a.x), np.asarray(b.x), atol=5e-3)

    def test_rejected_step_is_not_convergence(self):
        # f(w) = w⁴ − w from w0=0: singular Hessian at 0 makes the first CG
        # step walk to the boundary and get rejected. A rejected step must
        # shrink the radius and retry — not read as FUNCTION_VALUES_CONVERGED.
        vg = jax.value_and_grad(lambda w: jnp.sum(w**4 - w))
        res = TRON(OptimizerConfig(max_iterations=100)).optimize(
            vg, jnp.zeros(1, jnp.float32), self._hvp(vg)
        )
        np.testing.assert_allclose(float(res.x[0]), (1 / 4) ** (1 / 3), atol=1e-3)

    def test_poisson_with_tron(self, rng):
        from photon_tpu.ops.losses import PoissonLoss

        n, d = 150, 6
        x = jnp.asarray(rng.normal(size=(n, d)).astype(np.float32)) * 0.3
        w_true = jnp.asarray(rng.normal(size=d).astype(np.float32)) * 0.5
        y = jnp.asarray(rng.poisson(np.exp(np.asarray(x @ w_true))).astype(np.float32))
        vg = jax.value_and_grad(
            lambda w: jnp.sum(PoissonLoss.loss(x @ w, y)) + 0.5 * jnp.sum(w * w)
        )
        res = TRON(OptimizerConfig(max_iterations=100)).optimize(
            vg, jnp.zeros(d, jnp.float32), self._hvp(vg)
        )
        assert int(res.converged_reason) in (2, 3)
        # Gradient at the optimum is ~zero.
        assert float(res.grad_norm) < 1e-2 * max(1.0, float(res.value))


class TestDataPassCounter:
    """OptimizerResult.data_passes (device counter) must equal the number of
    actual feature-matrix touches, cross-checked by the host-callback counter
    at the matvec/rmatvec source (ops/pass_counter.py)."""

    def _problem(self, opt_type, reg_type, variance="NONE"):
        from photon_tpu.functions.problem import (
            GLMOptimizationProblem,
            VarianceComputationType,
        )
        from photon_tpu.optim import (
            OptimizerConfig,
            OptimizerType,
            RegularizationContext,
            RegularizationType,
        )
        from photon_tpu.types import TaskType

        return GLMOptimizationProblem(
            task=TaskType.LOGISTIC_REGRESSION,
            optimizer_type=opt_type,
            optimizer_config=OptimizerConfig(max_iterations=12, tolerance=0.0),
            regularization=RegularizationContext(reg_type),
            reg_weight=1.0,
            variance_type=VarianceComputationType[variance],
        )

    def _batch(self, rng, n=512, d=64, k=6):
        from photon_tpu.data.batch import LabeledBatch, SparseFeatures

        idx = rng.integers(0, d, size=(n, k)).astype(np.int32)
        val = (rng.normal(size=(n, k)) / np.sqrt(k)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        sf = SparseFeatures(jnp.asarray(idx), jnp.asarray(val), d)
        return LabeledBatch(
            sf,
            jnp.asarray(y),
            jnp.zeros((n,), jnp.float32),
            jnp.ones((n,), jnp.float32),
        ), d

    @pytest.mark.parametrize(
        "opt,reg",
        [("LBFGS", "L2"), ("OWLQN", "L1"), ("TRON", "L2")],
    )
    def test_device_counter_matches_source_counter(self, rng, opt, reg):
        from photon_tpu.ops import pass_counter
        from photon_tpu.optim import OptimizerType, RegularizationType

        problem = self._problem(OptimizerType[opt], RegularizationType[reg])
        batch, d = self._batch(rng)
        w0 = jnp.zeros((d,), jnp.float32)
        with pass_counter.counting() as counts:
            _, res = jax.jit(problem.run)(batch, w0)
            jax.block_until_ready(res.value)
        measured = counts["matvec"] + counts["rmatvec"] + counts["sq_rmatvec"]
        assert int(res.data_passes) == measured, (dict(counts), int(res.data_passes))
        assert measured > 0

    def test_scored_path_fewer_passes_than_plain(self, rng):
        """The incremental-score L-BFGS prices probes without data passes, so
        its pass count must not exceed the plain path's on the same solve."""
        from photon_tpu.functions.objective import GLMObjective
        from photon_tpu.ops.losses import LogisticLoss
        from photon_tpu.optim import LBFGS, OptimizerConfig

        batch, d = self._batch(rng)
        obj = GLMObjective(loss=LogisticLoss, l2_weight=1.0)
        cfg = OptimizerConfig(max_iterations=20, tolerance=0.0)
        w0 = jnp.zeros((d,), jnp.float32)
        plain = LBFGS(cfg).optimize(obj.bind(batch), w0)
        scored = LBFGS(cfg).optimize_scored(obj.score_space(batch), w0)
        assert int(scored.data_passes) <= int(plain.data_passes)
        # Scored path: init(2) + per-iter 2 (+1 refresh every 8th iter).
        it = int(scored.iterations)
        assert int(scored.data_passes) == 2 + 2 * it + it // 8
