"""Tail-based trace sampling (photon_tpu/obs/trace.py — ISSUE 18).

Coverage per the satellite checklist: the in-flight ring buffer stays
bounded under concurrent requests; promotion fires on a rolling-threshold
breach and on error (and NOT on a uniform-latency workload); spans
completed on a different thread than the request edge — the batcher
boundary — survive promotion intact, including shared batch-level spans
emitted exactly once; and promoted spans still honor the collector's
trace-size bound. Plus the per-stage labeled-histogram waterfall these
spans feed (docs/serving.md §"Latency waterfall").
"""
import json
import threading

import pytest

from photon_tpu.obs import (
    MetricsRegistry,
    TailSampler,
    install_tail_sampler,
    new_trace_id,
    tail_sampler,
    trace_context,
    trace_span,
    tracing,
    uninstall_tail_sampler,
)


@pytest.fixture(autouse=True)
def _no_leaked_sampler():
    uninstall_tail_sampler()
    yield
    uninstall_tail_sampler()


def _request(sampler, col, duration_s, error=False, n_spans=2):
    """One synthetic request: begin → emit spans under its trace id →
    finish with a verdict. Returns the trace id."""
    tid = new_trace_id()
    sampler.begin(tid)
    with trace_context(tid):
        for i in range(n_spans):
            with trace_span(f"serve.stage{i}", cat="serving"):
                pass
    return tid, sampler.finish(tid, duration_s, error=error)


def _span_names(col, tid):
    return sorted(e["name"] for e in col.events
                  if e.get("args", {}).get("trace_id") == tid
                  and e["ph"] == "X")


# ------------------------------------------------------------ promotion


def test_uniform_latency_workload_promotes_nothing():
    s = TailSampler(min_history=4, quantile=0.5)
    install_tail_sampler(s)
    with tracing() as col:
        for _ in range(20):
            _, promoted = _request(s, col, 0.010)
            assert not promoted
    assert s.promoted == 0 and s.discarded == 20
    # Every buffered span was diverted, none leaked into the collector.
    assert not [e for e in col.events
                if e["ph"] == "X" and e["name"].startswith("serve.")]


def test_threshold_breach_promotes_full_span_set():
    s = TailSampler(min_history=4, quantile=0.5)
    install_tail_sampler(s)
    with tracing() as col:
        for _ in range(8):
            _request(s, col, 0.010)
        tid, promoted = _request(s, col, 0.500, n_spans=3)
    assert promoted and s.promoted == 1
    assert _span_names(col, tid) == ["serve.stage0", "serve.stage1",
                                     "serve.stage2"]
    marks = [e for e in col.events
             if e["name"] == "photon.trace.tail_promoted"]
    assert len(marks) == 1
    assert marks[0]["args"]["trace_id"] == tid
    assert marks[0]["args"]["reason"] == "latency"
    assert marks[0]["args"]["spans"] == 3


def test_error_promotes_regardless_of_latency():
    s = TailSampler(min_history=4, quantile=0.5)
    install_tail_sampler(s)
    with tracing() as col:
        # No history at all: a latency verdict is impossible, the error
        # verdict must not be.
        tid, promoted = _request(s, col, 0.001, error=True)
    assert promoted and s.promoted_error == 1
    assert _span_names(col, tid) == ["serve.stage0", "serve.stage1"]
    mark = [e for e in col.events
            if e["name"] == "photon.trace.tail_promoted"][0]
    assert mark["args"]["reason"] == "error"


def test_threshold_needs_min_history():
    s = TailSampler(min_history=10, quantile=0.5)
    assert s.threshold_s() is None
    for _ in range(9):
        s.finish(new_trace_id(), 0.010)
    assert s.threshold_s() is None
    s.finish(new_trace_id(), 0.010)
    assert s.threshold_s() == pytest.approx(0.010)


# ------------------------------------------------------------ the ring


def test_ring_buffer_bound_and_fifo_eviction():
    s = TailSampler(capacity=8, min_history=4)
    install_tail_sampler(s)
    with tracing():
        tids = []
        for _ in range(30):
            tid = new_trace_id()
            s.begin(tid)
            tids.append(tid)
        assert s.snapshot()["inflight"] == 8
        assert s.evicted == 22
        # The survivors are the MOST RECENT begins (FIFO eviction), and
        # an evicted request's finish is a no-op, not a promotion.
        for _ in range(6):
            s.finish(new_trace_id(), 0.010)
        # An evicted request's finish feeds the window but can never
        # promote (its spans are gone) — a surviving one still can.
        assert not s.finish(tids[0], 0.010)
        assert s.finish(tids[-1], 99.0)


def test_ring_stays_bounded_under_concurrent_requests():
    s = TailSampler(capacity=16, min_history=4, quantile=0.5)
    install_tail_sampler(s)
    errors = []

    def client(wid):
        try:
            for i in range(50):
                tid = new_trace_id()
                s.begin(tid)
                with trace_context(tid):
                    with trace_span("serve.request", cat="serving"):
                        pass
                s.finish(tid, 0.001 * ((wid + i) % 7))
        except Exception as e:  # noqa: BLE001 - surfaced after join
            errors.append(e)

    with tracing():
        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not errors
    snap = s.snapshot()
    assert snap["inflight"] == 0
    assert snap["promoted"] + snap["discarded"] + snap["evicted"] == 400


def test_span_overflow_counted_not_unbounded():
    s = TailSampler(min_history=2, max_spans_per_request=4)
    install_tail_sampler(s)
    with tracing():
        tid = new_trace_id()
        s.begin(tid)
        with trace_context(tid):
            for i in range(10):
                with trace_span(f"serve.s{i}", cat="serving"):
                    pass
        s.finish(tid, 1.0, error=True)
    assert s.span_overflow == 6
    assert s.promoted == 1


# ----------------------------------------------- thread boundary + batch


def test_promoted_spans_survive_batcher_thread_boundary():
    """Spans completed on a WORKER thread (the micro-batcher) under the
    request's trace id must ride the same promotion as the request
    edge's own spans."""
    s = TailSampler(min_history=4, quantile=0.5)
    install_tail_sampler(s)
    with tracing() as col:
        for _ in range(8):
            _request(s, col, 0.010)
        tid = new_trace_id()
        s.begin(tid)

        def batcher_side():
            # No ambient trace_context on this thread — the explicit
            # trace_id arg is the propagation, exactly like
            # MicroBatcher's queue-wait/score spans.
            with trace_span("serve.queue_wait", cat="serving",
                            trace_id=tid):
                pass

        t = threading.Thread(target=batcher_side)
        t.start()
        t.join()
        with trace_context(tid):
            with trace_span("serve.request", cat="serving"):
                pass
        assert s.finish(tid, 0.500)
    assert _span_names(col, tid) == ["serve.queue_wait", "serve.request"]


def test_shared_batch_span_promoted_exactly_once():
    """A batch-level span carries trace_ids of every member; when two
    members both promote, the shared span must emit once."""
    s = TailSampler(min_history=4, quantile=0.5)
    install_tail_sampler(s)
    with tracing() as col:
        for _ in range(8):
            _request(s, col, 0.010)
        a, b = new_trace_id(), new_trace_id()
        s.begin(a)
        s.begin(b)
        with trace_span("serve.batch", cat="serving", rows=2,
                        trace_ids=[a, b]):
            pass
        assert s.finish(a, 0.400)
        assert s.finish(b, 0.500)
    batch = [e for e in col.events if e["name"] == "serve.batch"]
    assert len(batch) == 1
    assert sorted(batch[0]["args"]["trace_ids"]) == sorted([a, b])


# ------------------------------------------------------------- size bound


def test_promotion_honors_collector_size_bound(monkeypatch):
    monkeypatch.setenv("PHOTON_TRACE_MAX_BYTES", "2000")
    s = TailSampler(min_history=4, quantile=0.5)
    install_tail_sampler(s)
    with tracing() as col:
        for i in range(40):
            # Escalating durations: each breaches the rolling threshold,
            # so promotion pressure keeps hitting the byte bound.
            _request(s, col, 0.010 * (i + 1), n_spans=3)
    assert s.promoted > 5
    assert col._approx_bytes <= 2000
    assert col.dropped > 0


def test_env_install_and_explicit_precedence(monkeypatch):
    from photon_tpu.obs import start_tracing, stop_tracing

    monkeypatch.setenv("PHOTON_TRACE_TAIL", "1")
    monkeypatch.setenv("PHOTON_TRACE_TAIL_QUANTILE", "0.75")
    monkeypatch.setenv("PHOTON_TRACE_TAIL_WINDOW", "32")
    start_tracing()
    try:
        s = tail_sampler()
        assert s is not None
        assert s.quantile == 0.75
    finally:
        stop_tracing()
        uninstall_tail_sampler()
    # Malformed knobs degrade to defaults, never kill tracing.
    monkeypatch.setenv("PHOTON_TRACE_TAIL_QUANTILE", "banana")
    start_tracing()
    try:
        assert tail_sampler().quantile == 0.95
    finally:
        stop_tracing()
        uninstall_tail_sampler()
    # An explicitly installed sampler wins over the env default.
    mine = TailSampler(quantile=0.5)
    install_tail_sampler(mine)
    start_tracing()
    try:
        assert tail_sampler() is mine
    finally:
        stop_tracing()


# --------------------------------------------- stage waterfall histogram


def test_labeled_histogram_children_and_exposition():
    reg = MetricsRegistry()
    h = reg.histogram("serve_stage_latency_seconds", "waterfall")
    for ms, stage in ((1, "queue_wait"), (2, "queue_wait"), (50, "kernel")):
        h.observe(ms / 1e3, stage=stage)
    snap = h.snapshot_value()
    assert snap["queue_wait"]["count"] == 2
    assert snap["kernel"]["count"] == 1
    assert snap["kernel"]["p50_ms"] > snap["queue_wait"]["p50_ms"]
    prom = reg.to_prometheus()
    assert 'quantile="0.95",stage="queue_wait"' in prom
    assert 'photon_serve_stage_latency_seconds_count{stage="kernel"} 1' \
        in prom


def test_labeled_histogram_merges_and_deltas_across_shards():
    src = MetricsRegistry()
    h = src.histogram("lat", "labeled")
    h.observe(0.001, stage="kernel")
    h.observe(0.002, stage="queue_wait")
    agg = MetricsRegistry()
    agg.merge(src.dump_state(), anchor=1.0, shard_id="s1")
    first = agg.histogram("lat").snapshot_value()
    assert first["kernel"]["count"] == 1
    # Shard re-export after more samples: the delta fold must land ONLY
    # the new observations (idempotent re-merge contract).
    h.observe(0.003, stage="kernel")
    agg.merge(src.dump_state(), anchor=2.0, shard_id="s1")
    agg.merge(src.dump_state(), anchor=2.0, shard_id="s1")  # idempotent
    merged = agg.histogram("lat").snapshot_value()
    assert merged["kernel"]["count"] == 2
    assert merged["queue_wait"]["count"] == 1


def test_labeled_histogram_round_trips_registry_snapshot():
    reg = MetricsRegistry()
    reg.histogram("lat", "labeled").observe(0.004, stage="kernel")
    snap = json.loads(json.dumps(reg.snapshot()))  # JSON-serializable
    assert snap["lat"]["kernel"]["count"] == 1
