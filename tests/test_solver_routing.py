"""Entity-sub-batched Newton solves + measured cost-model solver routing.

Covers the round-6 contracts: chunked-vs-full solver agreement across all
four losses and both dtypes, inert padding lanes, the static chunked tiers
engaging where the budget gate refuses full buckets, the calibration race
(one-time, persisted, winner-respected, vmapped fallback when every Newton
variant is refused), the compile/solve timing split, and retrace-sentinel
silence across a multi-sweep fit (the chunk ladder is a closed set).
"""
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.random_effect import build_random_effect_dataset
from photon_tpu.functions.problem import GLMOptimizationProblem
from photon_tpu.game import newton_re, solver_routing, train_random_effects
from photon_tpu.game import random_effect as re_mod
from photon_tpu.obs import retrace
from photon_tpu.optim import (
    OptimizerConfig,
    OptimizerType,
    RegularizationContext,
    RegularizationType,
)
from photon_tpu.types import TaskType
from tests.test_random_effect import _make_entity_data

L2 = RegularizationContext(RegularizationType.L2)
L1 = RegularizationContext(RegularizationType.L1)


def _problem(task=TaskType.LOGISTIC_REGRESSION, reg=L2,
             optimizer=OptimizerType.LBFGS, reg_weight=0.5, max_iter=60):
    return GLMOptimizationProblem(
        task=task,
        optimizer_config=OptimizerConfig(max_iterations=max_iter),
        optimizer_type=optimizer,
        regularization=reg,
        reg_weight=reg_weight,
    )


def _bucket_setup(rng, dtype=np.float32, **data_kw):
    """One smallish dataset + the per-bucket solver inputs for bucket 0."""
    idx, val, labels, keys = _make_entity_data(rng, **data_kw)
    ds = build_random_effect_dataset(
        "userId", keys, idx, val, labels, global_dim=50, dtype=dtype)
    b = max(ds.buckets, key=lambda bb: bb.n_entities)
    offsets = jnp.zeros((ds.n_rows,), dtype)
    batches = b.local_batches(offsets)
    e, p = b.n_entities, b.local_dim
    w0 = jnp.zeros((e, p), b.val.dtype)
    mask = jnp.ones((e, p), b.val.dtype)
    return ds, b, batches, w0, mask


@pytest.mark.parametrize("task", list(TaskType))
@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_chunked_matches_full_primal_all_losses(rng, task, dtype):
    """Sub-batched primal Newton must agree with the full-bucket solve to
    solver tolerance for every loss family and both dtypes — chunking only
    re-batches the entity axis, it must not move any optimum."""
    problem = _problem(task=task)
    _, b, batches, w0, mask = _bucket_setup(rng, dtype=dtype)
    full_m, full_r = newton_re.fit_bucket_newton(problem, batches, w0, mask,
                                                 None)

    def fit_one(bb, w, m, pr):
        return newton_re.fit_bucket_newton(problem, bb, w, m, pr)

    # chunk=4 does not divide most entity counts -> padded tail exercised.
    ch_m, ch_r = newton_re.fit_bucket_in_chunks(fit_one, 4, batches, w0,
                                                mask, None)
    tol = 1e-10 if dtype == np.float64 else 2e-5
    np.testing.assert_allclose(np.asarray(ch_m.coefficients.means),
                               np.asarray(full_m.coefficients.means),
                               atol=tol)
    np.testing.assert_allclose(np.asarray(ch_r.value),
                               np.asarray(full_r.value), atol=tol)


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_chunked_matches_full_dual(rng, dtype):
    problem = _problem()
    _, b, batches, w0, mask = _bucket_setup(
        rng, dtype=dtype, max_rows=5, min_support=8)
    u_max = newton_re.u_max_for(
        newton_re.penalty_terms(problem, mask, None)[3])

    def fit_one(bb, w, m, pr):
        return newton_re.fit_bucket_newton_dual(problem, bb, w, m, pr, u_max)

    full_m, _ = fit_one(batches, w0, mask, None)
    ch_m, _ = newton_re.fit_bucket_in_chunks(fit_one, 4, batches, w0, mask,
                                             None)
    tol = 1e-9 if dtype == np.float64 else 5e-5
    np.testing.assert_allclose(np.asarray(ch_m.coefficients.means),
                               np.asarray(full_m.coefficients.means),
                               atol=tol)


def test_chunk_padding_lanes_inert(rng):
    """A chunk larger than the bucket (one fully padded chunk) and a
    non-dividing chunk must both reproduce the full solve exactly for the
    REAL lanes — padded lanes may not scatter anything into the restack."""
    problem = _problem()
    _, b, batches, w0, mask = _bucket_setup(rng)

    def fit_one(bb, w, m, pr):
        return newton_re.fit_bucket_newton(problem, bb, w, m, pr)

    full_m, full_r = fit_one(batches, w0, mask, None)
    e = w0.shape[0]
    for chunk in (e + 7, max(2, e - 1)):
        ch_m, ch_r = newton_re.fit_bucket_in_chunks(
            fit_one, chunk, batches, w0, mask, None)
        assert ch_m.coefficients.means.shape == full_m.coefficients.means.shape
        np.testing.assert_allclose(np.asarray(ch_m.coefficients.means),
                                   np.asarray(full_m.coefficients.means),
                                   atol=2e-5)
        # per-lane diagnostics restack to the true entity count too
        assert ch_r.value.shape == full_r.value.shape


def _train(problem, ds, init=None):
    offsets = jnp.zeros((ds.n_rows,), jnp.float32)
    model, results = train_random_effects(problem, ds, offsets,
                                          init_coefs=init)
    return model, results


def test_static_chunked_tier_engages_under_budget(rng, monkeypatch):
    """A bucket the FULL-bucket budget gate refuses must route to chunked
    Newton (not surrender to vmapped), and match the unconstrained solve."""
    problem = _problem()
    idx, val, labels, keys = _make_entity_data(rng, n_entities=12)
    ds = build_random_effect_dataset("userId", keys, idx, val, labels,
                                     global_dim=50, dtype=np.float32)
    ref_model, _ = _train(problem, ds)
    ref_solvers = {t["solver"] for t in re_mod.LAST_BUCKET_TIMINGS}
    assert ref_solvers == {"newton_primal"}

    monkeypatch.setenv("PHOTON_RE_CHUNK_LADDER", "2,4")
    # Tight budget: full buckets refused, 4-entity chunks fit.
    monkeypatch.setenv("PHOTON_RE_NEWTON_BUDGET_MB", "0.02")
    ch_model, _ = _train(problem, ds)
    rec = re_mod.LAST_BUCKET_TIMINGS
    assert all(t["solver"].startswith("newton") for t in rec), rec
    assert any(t["chunk"] is not None for t in rec), rec
    for a, b in zip(ch_model.bucket_coefs, ref_model.bucket_coefs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_compile_seconds_split(rng, monkeypatch):
    """First solve of a fresh shape reports compile_seconds > 0; an
    identical re-solve reports 0 (executable cache hit) — the split the
    bench stamps into artifacts."""
    problem = _problem(max_iter=59)  # unique static config -> fresh compile
    idx, val, labels, keys = _make_entity_data(rng, n_entities=7,
                                               global_dim=53)
    ds = build_random_effect_dataset("userId", keys, idx, val, labels,
                                     global_dim=53, dtype=np.float32)
    _train(problem, ds)
    first = [t["compile_seconds"] for t in re_mod.LAST_BUCKET_TIMINGS]
    assert any(c > 0 for c in first), first
    _train(problem, ds)
    second = [t["compile_seconds"] for t in re_mod.LAST_BUCKET_TIMINGS]
    assert all(c == 0 for c in second), second


@pytest.fixture
def measured(monkeypatch, tmp_path):
    table_path = str(tmp_path / "solver_costs.json")
    monkeypatch.setenv("PHOTON_RE_ROUTING", "measured")
    monkeypatch.setenv("PHOTON_RE_COST_TABLE", table_path)
    monkeypatch.setenv("PHOTON_RE_CHUNK_LADDER", "4,8")
    solver_routing.reset_process_table()
    yield table_path
    solver_routing.reset_process_table()


@pytest.mark.slow
def test_measured_routing_calibrates_once_then_persists(rng, measured,
                                                        monkeypatch):
    problem = _problem()
    idx, val, labels, keys = _make_entity_data(rng, n_entities=10)
    ds = build_random_effect_dataset("userId", keys, idx, val, labels,
                                     global_dim=50, dtype=np.float32)
    model, _ = _train(problem, ds)
    rec = re_mod.LAST_BUCKET_TIMINGS
    assert all(t["routing"] == "measured" for t in rec)
    assert any(t["calibrated"] for t in rec), rec
    assert all(t["calibration_seconds"] >= 0 for t in rec)
    # same optimum regardless of which candidate won the race
    with monkeypatch.context() as m:
        m.setenv("PHOTON_RE_ROUTING", "static")
        ref_model, _ = _train(problem, ds)
    for a, b in zip(model.bucket_coefs, ref_model.bucket_coefs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)

    # Second sweep of the same shapes: the table routes, nobody races.
    _train(problem, ds)
    assert not any(t["calibrated"] for t in re_mod.LAST_BUCKET_TIMINGS)

    # The race persisted; a fresh process (table reset + reload from the
    # env path) skips calibration entirely — the warm-restart contract.
    assert os.path.exists(measured)
    payload = json.load(open(measured))
    assert payload["version"] == 1 and payload["entries"]
    solver_routing.reset_process_table()
    _train(problem, ds)
    assert not any(t["calibrated"] for t in re_mod.LAST_BUCKET_TIMINGS)


def test_measured_routing_falls_back_without_newton(rng, measured):
    """When calibration refuses every Newton variant (L1 objective here),
    routing must hand the whole bucket to vmapped L-BFGS unchunked."""
    problem = _problem(reg=L1, optimizer=OptimizerType.OWLQN)
    idx, val, labels, keys = _make_entity_data(rng, n_entities=8)
    ds = build_random_effect_dataset("userId", keys, idx, val, labels,
                                     global_dim=50, dtype=np.float32)
    _train(problem, ds)
    rec = re_mod.LAST_BUCKET_TIMINGS
    assert {t["solver"] for t in rec} == {"vmapped_lbfgs"}, rec
    assert all(t["chunk"] is None for t in rec)
    assert not any(t["calibrated"] for t in rec)


def test_measured_routing_respects_seeded_winner(rng, measured):
    """A pre-seeded cost table IS the routing decision: absurdly expensive
    Newton entries force the vmapped baseline with no race run."""
    problem = _problem()
    idx, val, labels, keys = _make_entity_data(rng, n_entities=10)
    ds = build_random_effect_dataset("userId", keys, idx, val, labels,
                                     global_dim=50, dtype=np.float32)
    table = solver_routing.process_table()
    for b in ds.buckets:
        mask = jnp.ones((b.n_entities, b.local_dim), b.val.dtype)
        u_max = newton_re.u_max_for(
            newton_re.penalty_terms(problem, mask, None)[3])
        cands = solver_routing.candidates_for(problem, b, None, u_max)
        assert any(c.solver.startswith("newton") for c in cands)
        key = solver_routing.shape_class(b)
        for c in cands:
            cost = 1e-9 if c.solver == "vmapped_lbfgs" else 1e9
            table.record(key, c, cost)
    _train(problem, ds)
    rec = re_mod.LAST_BUCKET_TIMINGS
    assert {t["solver"] for t in rec} == {"vmapped_lbfgs"}, rec
    assert all(t["chunk"] is not None for t in rec)  # chunked baseline
    assert not any(t["calibrated"] for t in rec)


def test_cost_table_roundtrip(tmp_path):
    t = solver_routing.SolverCostTable()
    c1 = solver_routing.Candidate("newton_dual", 4096)
    c2 = solver_routing.Candidate("vmapped_lbfgs", 4096)
    t.record("s16k6p32:float32", c1, 1.5e-5)
    t.record("s16k6p32:float32", c2, 9.0e-5)
    assert t.winner("s16k6p32:float32", [c1, c2]) == c1
    assert t.winner("s16k6p32:float32", [c2]) == c2       # feasibility-aware
    assert t.winner("other", [c1, c2]) is None
    # A feasible candidate with NO recorded cost forces a (partial) race:
    # a table persisted under a smaller budget must not permanently pin
    # routing to the only solver it happened to measure.
    c3 = solver_routing.Candidate("newton_primal", 4096)
    assert t.winner("s16k6p32:float32", [c1, c2, c3]) is None
    path = str(tmp_path / "costs.json")
    t.save(path)
    t2 = solver_routing.SolverCostTable()
    t2.load(path)
    assert t2.costs("s16k6p32:float32") == t.costs("s16k6p32:float32")
    with pytest.raises(ValueError):
        t2.load_json({"version": 99})


def test_chunk_ladder_env(monkeypatch):
    monkeypatch.setenv("PHOTON_RE_CHUNK_LADDER", "64, 8,512")
    assert newton_re.chunk_ladder() == (8, 64, 512)
    monkeypatch.setenv("PHOTON_RE_CHUNK_LADDER", "0,8")
    with pytest.raises(ValueError):
        newton_re.chunk_ladder()
    monkeypatch.delenv("PHOTON_RE_CHUNK_LADDER")
    assert newton_re.chunk_ladder() == newton_re._DEFAULT_CHUNK_LADDER


def test_routing_mode_validation(monkeypatch):
    monkeypatch.setenv("PHOTON_RE_ROUTING", "sometimes")
    with pytest.raises(ValueError):
        solver_routing.routing_mode()
    monkeypatch.setenv("PHOTON_RE_ROUTING", "measured")
    assert solver_routing.routing_mode() == "measured"
    monkeypatch.delenv("PHOTON_RE_ROUTING")
    assert solver_routing.routing_mode() == "static"


@pytest.mark.slow
def test_retrace_quiet_across_sweeps_with_chunking(rng, monkeypatch):
    """Acceptance check: across a 3-sweep descent with chunked Newton
    solves, the retrace sentinel must count ZERO retraces-after-warmup for
    the bucket kernels — the chunk ladder is closed, so sweep 1 compiles
    everything sweeps 2-3 need."""
    from photon_tpu.estimators.config import (
        FixedEffectDataConfig,
        GLMOptimizationConfiguration,
        RandomEffectDataConfig,
    )
    from photon_tpu.estimators.game_estimator import GameEstimator
    from tests.test_checkpoint import _bundle

    def estimator(n_sweeps):
        return GameEstimator(
            task=TaskType.LOGISTIC_REGRESSION,
            coordinate_data_configs={
                "fixed": FixedEffectDataConfig("g"),
                "perUser": RandomEffectDataConfig(re_type="userId",
                                                  feature_shard="g"),
            },
            n_sweeps=n_sweeps,
        )

    cfg = {
        "fixed": GLMOptimizationConfiguration(
            regularization=L2, reg_weight=1.0, max_iterations=8),
        "perUser": GLMOptimizationConfiguration(
            regularization=L2, reg_weight=1.0, max_iterations=8),
    }
    bundle = _bundle(n_users=24, rows_per_user=8)
    # Scout pass: learn the bucket shapes so the budget below is computed,
    # not guessed — full buckets must be refused while 8-entity chunks fit.
    estimator(1).fit(bundle, None, [cfg])
    shapes = [(t["row_slots"] // t["entities"], t["local_dim"], t["entities"])
              for t in re_mod.LAST_BUCKET_TIMINGS]
    assert any(e > 8 for _, _, e in shapes), shapes
    budget_b = 1.5 * max(
        newton_re._primal_need_bytes(8, s, p, 4.0) for s, p, _ in shapes)
    monkeypatch.setenv("PHOTON_RE_CHUNK_LADDER", "4,8")
    monkeypatch.setenv("PHOTON_RE_NEWTON_BUDGET_MB", str(budget_b / 1e6))

    retrace.reset()
    estimator(3).fit(bundle, None, [cfg])
    assert any(t["chunk"] is not None for t in re_mod.LAST_BUCKET_TIMINGS)
    compiled = sum(retrace.traces(k) for k in retrace.RE_SOLVER_KERNELS)
    assert compiled > 0  # the solves really went through watched kernels
    for k in retrace.RE_SOLVER_KERNELS:
        assert retrace.retraces_after_warmup(k) == 0, k
