"""End-to-end GLMOptimizationProblem tests: each task type trains to the
sklearn/scipy optimum; variance computation matches the inverse Hessian.
The single-chip degenerate case of the reference's ⟦FixedEffectCoordinate⟧
training path (SURVEY.md §7 stage 3).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.data.batch import make_dense_batch
from photon_tpu.functions.objective import intercept_reg_mask
from photon_tpu.functions.problem import (
    GLMOptimizationProblem,
    VarianceComputationType,
)
from photon_tpu.optim import (
    L2RegularizationContext,
    L1RegularizationContext,
    OptimizerConfig,
    OptimizerType,
)
from photon_tpu.types import TaskType


def _with_intercept(x):
    return np.concatenate([np.ones((x.shape[0], 1)), x], axis=1)


def test_logistic_matches_sklearn(rng):
    from sklearn.linear_model import LogisticRegression

    n, d = 400, 6
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (1 / (1 + np.exp(-(x @ w + 0.3))) > rng.uniform(size=n)).astype(float)
    lam = 1.0
    xd = _with_intercept(x)
    batch = make_dense_batch(xd, y, dtype=jnp.float64)
    prob = GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION,
        optimizer_type=OptimizerType.LBFGS,
        optimizer_config=OptimizerConfig(max_iterations=300, tolerance=1e-10),
        regularization=L2RegularizationContext,
        reg_weight=lam,
        reg_mask=intercept_reg_mask(d + 1, 0),
    )
    model, res = prob.run(batch, jnp.zeros(d + 1, jnp.float64))
    ref = LogisticRegression(C=1.0 / lam, tol=1e-10, max_iter=5000).fit(x, y)
    np.testing.assert_allclose(model.coefficients.means[0],
                               ref.intercept_[0], atol=2e-3)
    np.testing.assert_allclose(model.coefficients.means[1:],
                               ref.coef_[0], atol=2e-3)


def test_linear_matches_ridge_closed_form(rng):
    n, d = 200, 5
    x = rng.normal(size=(n, d))
    y = x @ rng.normal(size=d) + 0.1 * rng.normal(size=n)
    lam = 2.0
    batch = make_dense_batch(x, y, dtype=jnp.float64)
    prob = GLMOptimizationProblem(
        task=TaskType.LINEAR_REGRESSION,
        optimizer_type=OptimizerType.TRON,
        optimizer_config=OptimizerConfig(max_iterations=100, tolerance=1e-12),
        regularization=L2RegularizationContext,
        reg_weight=lam,
        variance_type=VarianceComputationType.FULL,
    )
    model, _ = prob.run(batch, jnp.zeros(d, jnp.float64))
    # Closed form: (XᵀX + λI)⁻¹ Xᵀy.
    w_star = np.linalg.solve(x.T @ x + lam * np.eye(d), x.T @ y)
    np.testing.assert_allclose(model.coefficients.means, w_star, atol=1e-6)
    # FULL variances = diag((XᵀX + λI)⁻¹) for squared loss.
    v_star = np.diag(np.linalg.inv(x.T @ x + lam * np.eye(d)))
    np.testing.assert_allclose(model.coefficients.variances, v_star, rtol=1e-4)


def test_poisson_owlqn_sparsifies(rng):
    n, d = 300, 10
    x = rng.normal(size=(n, d)) * 0.4
    w_true = np.zeros(d)
    w_true[:3] = [0.8, -0.5, 0.6]
    y = rng.poisson(np.exp(x @ w_true)).astype(float)
    batch = make_dense_batch(x, y, dtype=jnp.float64)
    prob = GLMOptimizationProblem(
        task=TaskType.POISSON_REGRESSION,
        optimizer_type=OptimizerType.OWLQN,
        optimizer_config=OptimizerConfig(max_iterations=200),
        regularization=L1RegularizationContext,
        reg_weight=15.0,
    )
    model, _ = prob.run(batch, jnp.zeros(d, jnp.float64))
    means = np.asarray(model.coefficients.means)
    assert (means == 0.0).sum() >= 4, means
    assert np.abs(means[:3]).min() > 0.0


def test_simple_variances(rng):
    n, d = 150, 4
    x = rng.normal(size=(n, d))
    y = rng.integers(0, 2, n).astype(float)
    batch = make_dense_batch(x, y, dtype=jnp.float64)
    prob = GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION,
        regularization=L2RegularizationContext,
        reg_weight=0.5,
        variance_type=VarianceComputationType.SIMPLE,
    )
    model, _ = prob.run(batch, jnp.zeros(d, jnp.float64))
    w = model.coefficients.means
    z = x @ np.asarray(w)
    s = 1 / (1 + np.exp(-z))
    diag_h = (s * (1 - s))[:, None] * x**2
    expect = 1.0 / (diag_h.sum(0) + 0.5)
    np.testing.assert_allclose(model.coefficients.variances, expect, rtol=1e-5)


def test_smoothed_hinge_trains(rng):
    n, d = 200, 5
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (x @ w > 0).astype(float)
    batch = make_dense_batch(x, y, dtype=jnp.float64)
    prob = GLMOptimizationProblem(
        task=TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        regularization=L2RegularizationContext,
        reg_weight=0.1,
        optimizer_config=OptimizerConfig(max_iterations=200),
    )
    model, res = prob.run(batch, jnp.zeros(d, jnp.float64))
    acc = float(((x @ np.asarray(model.coefficients.means) > 0) == y).mean())
    assert acc > 0.95


def test_full_variance_refuses_wide_models(rng, monkeypatch):
    """FULL variance on a wide shard fails fast with guidance instead of
    letting XLA materialize a D x D Hessian (VERDICT round-2 weak #6)."""
    import photon_tpu.functions.problem as problem_mod

    monkeypatch.setattr(problem_mod, "FULL_VARIANCE_MAX_DIM", 64)
    n, d = 30, 65
    x = rng.normal(size=(n, d))
    y = x[:, 0] + 0.1 * rng.normal(size=n)
    batch = make_dense_batch(x, y, dtype=jnp.float32)
    prob = GLMOptimizationProblem(
        task=TaskType.LINEAR_REGRESSION,
        optimizer_type=OptimizerType.LBFGS,
        optimizer_config=OptimizerConfig(max_iterations=5),
        regularization=L2RegularizationContext,
        reg_weight=1.0,
        variance_type=VarianceComputationType.FULL,
    )
    with pytest.raises(ValueError, match="FULL variance.*SIMPLE"):
        prob.run(batch, jnp.zeros(d, jnp.float32))


def test_reg_weight_sweep_shares_one_executable(rng, monkeypatch):
    """fit() treats reg_weight as a dynamic argument: a λ grid must not
    re-trace per point (the legacy driver's sweep relies on this). Traces
    are counted by wrapping ``run`` — it executes once per trace and never
    on a jit-cache hit."""
    import photon_tpu.functions.problem as pm

    n, d = 60, 4
    x = rng.normal(size=(n, d))
    y = x @ rng.normal(size=d)
    batch = make_dense_batch(x, y, dtype=jnp.float32)
    pm._fit_jitted.clear_cache()
    base = GLMOptimizationProblem(
        task=TaskType.LINEAR_REGRESSION,
        optimizer_type=OptimizerType.LBFGS,
        optimizer_config=OptimizerConfig(max_iterations=30),
        regularization=L2RegularizationContext,
        reg_weight=0.0,
    )
    import dataclasses as dc

    traces = {"n": 0}
    orig_run = pm.GLMOptimizationProblem.run

    def counting_run(self, *a, **k):
        traces["n"] += 1
        return orig_run(self, *a, **k)

    monkeypatch.setattr(pm.GLMOptimizationProblem, "run", counting_run)
    values = []
    for lam in (0.01, 0.1, 1.0, 10.0):
        model, _ = dc.replace(base, reg_weight=lam).fit(
            batch, jnp.zeros(d, jnp.float32)
        )
        values.append(np.asarray(model.coefficients.means))
    monkeypatch.setattr(pm.GLMOptimizationProblem, "run", orig_run)
    assert traces["n"] == 1
    # λ actually took effect: heavier regularization shrinks the solution
    norms = [np.linalg.norm(v) for v in values]
    assert norms[0] > norms[-1] * 1.05
    # and each grid point matches a fresh direct (uncached) solve
    direct, _ = jax.jit(dc.replace(base, reg_weight=10.0).run)(
        batch, jnp.zeros(d, jnp.float32)
    )
    np.testing.assert_allclose(
        values[-1], np.asarray(direct.coefficients.means), atol=1e-6
    )


def test_run_reg_weight_override_keeps_l1_guard(rng):
    """A concrete reg_weight override participates in the L1-routing guard:
    enabling L1 through the override on a smooth optimizer must still raise."""
    n, d = 40, 3
    x = rng.normal(size=(n, d))
    y = x @ rng.normal(size=d)
    batch = make_dense_batch(x, y, dtype=jnp.float32)
    prob = GLMOptimizationProblem(
        task=TaskType.LINEAR_REGRESSION,
        optimizer_type=OptimizerType.LBFGS,
        optimizer_config=OptimizerConfig(max_iterations=5),
        regularization=L1RegularizationContext,
        reg_weight=0.0,
    )
    with pytest.raises(ValueError, match="OWLQN"):
        prob.run(batch, jnp.zeros(d, jnp.float32), reg_weight=1.0)
    # and a zero override on a nonzero-configured problem is legal
    import dataclasses as dc

    dc.replace(prob, reg_weight=1.0).run(
        batch, jnp.zeros(d, jnp.float32), reg_weight=0.0
    )
