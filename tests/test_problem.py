"""End-to-end GLMOptimizationProblem tests: each task type trains to the
sklearn/scipy optimum; variance computation matches the inverse Hessian.
The single-chip degenerate case of the reference's ⟦FixedEffectCoordinate⟧
training path (SURVEY.md §7 stage 3).
"""
import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.batch import make_dense_batch
from photon_tpu.functions.objective import intercept_reg_mask
from photon_tpu.functions.problem import (
    GLMOptimizationProblem,
    VarianceComputationType,
)
from photon_tpu.optim import (
    L2RegularizationContext,
    L1RegularizationContext,
    OptimizerConfig,
    OptimizerType,
)
from photon_tpu.types import TaskType


def _with_intercept(x):
    return np.concatenate([np.ones((x.shape[0], 1)), x], axis=1)


def test_logistic_matches_sklearn(rng):
    from sklearn.linear_model import LogisticRegression

    n, d = 400, 6
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (1 / (1 + np.exp(-(x @ w + 0.3))) > rng.uniform(size=n)).astype(float)
    lam = 1.0
    xd = _with_intercept(x)
    batch = make_dense_batch(xd, y, dtype=jnp.float64)
    prob = GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION,
        optimizer_type=OptimizerType.LBFGS,
        optimizer_config=OptimizerConfig(max_iterations=300, tolerance=1e-10),
        regularization=L2RegularizationContext,
        reg_weight=lam,
        reg_mask=intercept_reg_mask(d + 1, 0),
    )
    model, res = prob.run(batch, jnp.zeros(d + 1, jnp.float64))
    ref = LogisticRegression(C=1.0 / lam, tol=1e-10, max_iter=5000).fit(x, y)
    np.testing.assert_allclose(model.coefficients.means[0],
                               ref.intercept_[0], atol=2e-3)
    np.testing.assert_allclose(model.coefficients.means[1:],
                               ref.coef_[0], atol=2e-3)


def test_linear_matches_ridge_closed_form(rng):
    n, d = 200, 5
    x = rng.normal(size=(n, d))
    y = x @ rng.normal(size=d) + 0.1 * rng.normal(size=n)
    lam = 2.0
    batch = make_dense_batch(x, y, dtype=jnp.float64)
    prob = GLMOptimizationProblem(
        task=TaskType.LINEAR_REGRESSION,
        optimizer_type=OptimizerType.TRON,
        optimizer_config=OptimizerConfig(max_iterations=100, tolerance=1e-12),
        regularization=L2RegularizationContext,
        reg_weight=lam,
        variance_type=VarianceComputationType.FULL,
    )
    model, _ = prob.run(batch, jnp.zeros(d, jnp.float64))
    # Closed form: (XᵀX + λI)⁻¹ Xᵀy.
    w_star = np.linalg.solve(x.T @ x + lam * np.eye(d), x.T @ y)
    np.testing.assert_allclose(model.coefficients.means, w_star, atol=1e-6)
    # FULL variances = diag((XᵀX + λI)⁻¹) for squared loss.
    v_star = np.diag(np.linalg.inv(x.T @ x + lam * np.eye(d)))
    np.testing.assert_allclose(model.coefficients.variances, v_star, rtol=1e-4)


def test_poisson_owlqn_sparsifies(rng):
    n, d = 300, 10
    x = rng.normal(size=(n, d)) * 0.4
    w_true = np.zeros(d)
    w_true[:3] = [0.8, -0.5, 0.6]
    y = rng.poisson(np.exp(x @ w_true)).astype(float)
    batch = make_dense_batch(x, y, dtype=jnp.float64)
    prob = GLMOptimizationProblem(
        task=TaskType.POISSON_REGRESSION,
        optimizer_type=OptimizerType.OWLQN,
        optimizer_config=OptimizerConfig(max_iterations=200),
        regularization=L1RegularizationContext,
        reg_weight=15.0,
    )
    model, _ = prob.run(batch, jnp.zeros(d, jnp.float64))
    means = np.asarray(model.coefficients.means)
    assert (means == 0.0).sum() >= 4, means
    assert np.abs(means[:3]).min() > 0.0


def test_simple_variances(rng):
    n, d = 150, 4
    x = rng.normal(size=(n, d))
    y = rng.integers(0, 2, n).astype(float)
    batch = make_dense_batch(x, y, dtype=jnp.float64)
    prob = GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION,
        regularization=L2RegularizationContext,
        reg_weight=0.5,
        variance_type=VarianceComputationType.SIMPLE,
    )
    model, _ = prob.run(batch, jnp.zeros(d, jnp.float64))
    w = model.coefficients.means
    z = x @ np.asarray(w)
    s = 1 / (1 + np.exp(-z))
    diag_h = (s * (1 - s))[:, None] * x**2
    expect = 1.0 / (diag_h.sum(0) + 0.5)
    np.testing.assert_allclose(model.coefficients.variances, expect, rtol=1e-5)


def test_smoothed_hinge_trains(rng):
    n, d = 200, 5
    x = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (x @ w > 0).astype(float)
    batch = make_dense_batch(x, y, dtype=jnp.float64)
    prob = GLMOptimizationProblem(
        task=TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
        regularization=L2RegularizationContext,
        reg_weight=0.1,
        optimizer_config=OptimizerConfig(max_iterations=200),
    )
    model, res = prob.run(batch, jnp.zeros(d, jnp.float64))
    acc = float(((x @ np.asarray(model.coefficients.means) > 0) == y).mean())
    assert acc > 0.95
