"""Memory-pressure resilience (runtime/memory_guard; docs/robustness.md
§"Memory pressure"): OOM classification, the bounded/sticky downshift
ladder, the device-memory watchdog's spill + shed thresholds, the live
sweep-cache budget clamp, and the supervisor's restart-cannot-fix-OOM
policy. The per-site ladder drills (RE chunk tier, out-of-core rechunk)
run here at tiny shapes; the end-to-end chaos drills live in
tests/test_chaos.py / test_serving.py / test_online.py (``-m chaos``).
"""
import json

import jax.numpy as jnp
import numpy as np
import pytest

from photon_tpu.faults import (
    DeviceOomError,
    FaultPlan,
    FaultSpec,
    active_plan,
)
from photon_tpu.obs.metrics import REGISTRY
from photon_tpu.runtime import backend_guard as bg
from photon_tpu.runtime import memory_guard as mg
from photon_tpu.supervisor import (
    RecoveryJournal,
    RestartPolicy,
    RestartsExhausted,
    RunSupervisor,
    run_with_recovery,
)


@pytest.fixture(autouse=True)
def _fresh_guard_state():
    """Sticky downshifts are process-global by design; tests must not
    leak degraded plans into each other."""
    mg.reset_state()
    yield
    mg.reset_state()


def _fake_stats(in_use: float, limit: float = 1000.0):
    return lambda: {"bytes_in_use": float(in_use),
                    "bytes_limit": float(limit),
                    "watermark": float(in_use) / float(limit)}


# ------------------------------------------------------------ classification


def test_device_oom_classifies_oom_by_type():
    assert bg.classify_backend_error(DeviceOomError("boom")) == bg.CAUSE_OOM
    assert mg.is_oom(DeviceOomError("boom"))
    assert mg.is_oom(MemoryError("host oom"))
    assert mg.is_oom(RuntimeError(
        "RESOURCE_EXHAUSTED: out of memory allocating 16G"))
    # A device loss is NOT an OOM — it takes the PR 8 recovery path.
    assert not mg.is_oom(RuntimeError("device was lost"))


def test_device_oom_is_supervisor_retryable():
    """DeviceOomError subclasses RuntimeError (like XlaRuntimeError) so
    the restart policy admits it — the OOM-specific handling then decides
    what a 'retry' means."""
    assert RestartPolicy().is_retryable(DeviceOomError("boom"))


def test_fault_plan_device_oom_spec_roundtrips():
    plan = FaultPlan(seed=0, specs=[
        FaultSpec(site="re.solve", error="device_oom", count=1)])
    back = FaultPlan.from_json(plan.to_json())
    assert back.specs[0].error == "device_oom"
    with active_plan(back) as inj:
        from photon_tpu.faults import fault_point

        with pytest.raises(DeviceOomError):
            fault_point("re.solve")
        assert inj.fired("re.solve") == 1


# ---------------------------------------------------------------- downshifter


def test_downshifter_bounded_and_counted(monkeypatch):
    monkeypatch.setenv("PHOTON_OOM_MAX_DOWNSHIFTS", "2")
    before = REGISTRY.counter("oom_downshifts_total").value(
        site="t.site", cause="oom")
    d = mg.downshifter("t.site")
    err = DeviceOomError("boom")
    assert d.absorb(err, before="a", after="b")
    assert d.absorb(err, before="b", after="c")
    assert not d.absorb(err, before="c", after="d")  # budget spent
    assert REGISTRY.counter("oom_downshifts_total").value(
        site="t.site", cause="oom") == before + 2
    # Same site resolves to the same (process-global) budget.
    assert mg.downshifter("t.site") is d


def test_downshift_journal_rows(tmp_path):
    path = str(tmp_path / "recovery.jsonl")
    mg.set_journal(RecoveryJournal(path))
    d = mg.downshifter("t.journal")
    assert d.absorb(DeviceOomError("boom"),
                    before="newton_dual@4096", after="newton_dual@1024")
    rows = [json.loads(x) for x in open(path).read().splitlines()]
    assert len(rows) == 1
    assert rows[0]["event"] == "oom_downshift"
    assert rows[0]["site"] == "t.journal"
    assert rows[0]["before"] == "newton_dual@4096"
    assert rows[0]["after"] == "newton_dual@1024"
    assert rows[0]["cause"] == "oom"


def test_sticky_plan_roundtrip():
    assert mg.sticky_plan("re.solve") is None
    mg.set_sticky_plan("re.solve", {"chunk": 1024})
    assert mg.sticky_plan("re.solve") == {"chunk": 1024}
    mg.reset_state()
    assert mg.sticky_plan("re.solve") is None


def test_oom_next_tier_ladder(monkeypatch):
    """full -> next-smaller blessed chunk -> ... -> vmapped -> exhausted."""
    monkeypatch.setenv("PHOTON_RE_CHUNK_LADDER", "256,1024,4096")
    from photon_tpu.game.random_effect import _oom_next_tier

    e = 5000
    assert _oom_next_tier("newton_dual", None, e) == ("newton_dual", 4096)
    assert _oom_next_tier("newton_dual", 4096, e) == ("newton_dual", 1024)
    assert _oom_next_tier("newton_dual", 256, e) == ("vmapped_lbfgs", 256)
    assert _oom_next_tier("vmapped_lbfgs", 256, e) is None
    # Small buckets fall straight to the FULL vmapped solve.
    assert _oom_next_tier("newton_primal", None, 100) == (
        "vmapped_lbfgs", None)
    assert _oom_next_tier("vmapped_lbfgs", None, 100) is None
    # A big vmapped bucket still has chunked tiers below it.
    assert _oom_next_tier("vmapped_lbfgs", None, e) == (
        "vmapped_lbfgs", 4096)


def test_apply_sticky_plan_clamps():
    from photon_tpu.game.random_effect import _apply_sticky_plan

    assert _apply_sticky_plan(("newton_dual", None), None, 5000) == (
        "newton_dual", None)
    assert _apply_sticky_plan(
        ("newton_dual", None), {"chunk": 1024}, 5000) == (
        "newton_dual", 1024)
    # A bucket already under the cap keeps its full-bucket plan.
    assert _apply_sticky_plan(
        ("newton_dual", None), {"chunk": 1024}, 500) == ("newton_dual", None)
    assert _apply_sticky_plan(
        ("newton_primal", 4096),
        {"chunk": 256, "solver": "vmapped_lbfgs"}, 5000,
    ) == ("vmapped_lbfgs", 256)


# ------------------------------------------------------------------ watchdog


def test_memory_guard_thresholds():
    g = mg.MemoryGuard(stats_fn=_fake_stats(900), min_sample_interval_s=0.0)
    assert g.watermark() == pytest.approx(0.9)
    assert g.under_pressure() and not g.should_shed()
    g = mg.MemoryGuard(stats_fn=_fake_stats(990), min_sample_interval_s=0.0)
    before = REGISTRY.counter("memory_pressure_sheds_total").value()
    assert g.should_shed()
    assert REGISTRY.counter(
        "memory_pressure_sheds_total").value() == before + 1


def test_memory_guard_no_stats_backend_is_quiet():
    """CPU (no memory_stats): nothing sheds, nothing spills, gauges read
    0 watermark — the classified-OOM ladder alone carries the story."""
    g = mg.MemoryGuard(stats_fn=lambda: None, min_sample_interval_s=0.0)
    assert g.watermark() is None
    assert not g.under_pressure() and not g.should_shed()
    assert g.check() == {"available": False, "watermark": None,
                         "spilled_bytes": 0}


def test_memory_guard_exports_gauges():
    g = mg.MemoryGuard(stats_fn=_fake_stats(850), min_sample_interval_s=0.0)
    g.sample(force=True)
    assert REGISTRY.gauge("device_memory_bytes_in_use").value() == 850.0
    assert REGISTRY.gauge("device_memory_bytes_limit").value() == 1000.0
    assert REGISTRY.gauge("device_memory_watermark").value() == 0.85


def test_watchdog_spills_sweep_cache_pins_above_high_water():
    from photon_tpu.data.device_cache import DeviceSweepCache

    cache = DeviceSweepCache(budget_bytes=1 << 20)
    host = [np.zeros(64, np.float32) for _ in range(4)]
    for h in host:
        cache.get_or_put(("t", id(h)), h.nbytes,
                         lambda h=h: jnp.asarray(h), retain=h)
    assert cache.resident_bytes == 4 * 256
    # 900/1000 in use, high water 0.85 -> target: free >= 50 bytes; the
    # oldest pin (256 bytes) covers it.
    g = mg.MemoryGuard(stats_fn=_fake_stats(900), min_sample_interval_s=0.0)
    report = g.check()
    assert report["spilled_bytes"] >= 50
    assert cache.resident_bytes < 4 * 256
    # The spill is sticky: a re-lookup of the shed key streams (miss),
    # and does NOT re-pin.
    shed_key = ("t", id(host[0]))
    resident_after = cache.resident_bytes
    cache.get_or_put(shed_key, host[0].nbytes,
                     lambda: jnp.asarray(host[0]), retain=host[0])
    assert cache.resident_bytes == resident_after
    cache.release()


def test_shed_exempts_dataset_mirrors(rng):
    """Mirrors are identity-pinned (score/train identity contract) — the
    pressure valve must only spill chunk entries."""
    from photon_tpu.data.device_cache import DeviceSweepCache
    from photon_tpu.data.random_effect import build_random_effect_dataset
    from tests.test_random_effect import _make_entity_data

    idx, val, labels, keys = _make_entity_data(rng, n_entities=4)
    ds = build_random_effect_dataset(
        "userId", keys, idx, val, labels, global_dim=50,
        host_resident=True)
    cache = DeviceSweepCache(budget_bytes=1 << 24)
    mirror = cache.dataset_mirror(ds)
    h = np.zeros(64, np.float32)
    cache.get_or_put(("t", id(h)), h.nbytes, lambda: jnp.asarray(h),
                     retain=h)
    cache.shed(1 << 30)  # ask for everything
    # The chunk pin went; the mirror stayed — and stays the SAME object.
    assert cache.dataset_mirror(ds) is mirror
    stats = cache.stats()
    assert stats["entries"] == 1  # the mirror's entry survived
    cache.release()


# -------------------------------------------------------------- budget clamp


def test_effective_sweep_budget_clamps_to_device_limit(monkeypatch, caplog):
    monkeypatch.setenv("PHOTON_SWEEP_CACHE_DEVICE_FRACTION", "0.5")
    mg.guard().stats_fn = _fake_stats(100, limit=1000.0)
    mg.guard().min_sample_interval_s = 0.0
    import logging

    with caplog.at_level(logging.WARNING, logger="photon_tpu.memory_guard"):
        assert mg.effective_sweep_budget(10_000) == 500  # clamped
        assert mg.effective_sweep_budget(400) == 400     # fits
    warnings = [r for r in caplog.records if "clamping" in r.message]
    assert len(warnings) == 1  # one-time warning


def test_effective_sweep_budget_no_stats_keeps_requested():
    mg.guard().stats_fn = lambda: None
    mg.guard().min_sample_interval_s = 0.0
    assert mg.effective_sweep_budget(12345) == 12345


def test_pre_degrade_halves_budget_scale_and_caps_ladder(tmp_path):
    path = str(tmp_path / "recovery.jsonl")
    mg.set_journal(RecoveryJournal(path))
    mg.guard().stats_fn = lambda: None
    plan = mg.pre_degrade_for_restart("test oom")
    assert plan["sweep_cache_budget_scale"] == 0.5
    assert plan["re_chunk_cap"] in mg.sticky_plan("re.solve").values()
    # The degraded scale reaches a NEW cache's effective budget.
    assert mg.effective_sweep_budget(1000) == 500
    # Another pre-degrade steps one more tier down + halves again.
    plan2 = mg.pre_degrade_for_restart("again")
    assert plan2["sweep_cache_budget_scale"] == 0.25
    assert plan2["re_chunk_cap"] < plan["re_chunk_cap"]
    rows = [json.loads(x) for x in open(path).read().splitlines()]
    assert [r["event"] for r in rows] == ["oom_predegrade", "oom_predegrade"]


# ------------------------------------------------------------- supervisor


def test_supervisor_oom_restarts_once_predegraded_no_backoff(tmp_path):
    sleeps = []
    calls = []

    def attempt(i):
        calls.append(i)
        if i == 0:
            raise DeviceOomError("RESOURCE_EXHAUSTED: injected")
        # The retry runs PRE-DEGRADED: budget scale halved, ladder capped.
        assert mg.sweep_budget_scale() == 0.5
        assert mg.sticky_plan("re.solve") is not None
        return "survived"

    journal = str(tmp_path / "recovery.jsonl")
    # compile_store=None: this test pins the OOM journal sequence; a store
    # left active by another test would add its own prewarm row.
    sup = RunSupervisor(
        RestartPolicy(max_restarts=3, backoff_seconds=5.0, jitter=False),
        journal=journal, sleep=sleeps.append, compile_store=None,
    )
    assert sup.run(attempt) == "survived"
    assert calls == [0, 1]
    assert sleeps == []  # no backoff burned on a deterministic failure
    rows = [json.loads(x) for x in open(journal).read().splitlines()]
    events = [r["event"] for r in rows]
    assert events == ["attempt_start", "attempt_failed", "oom_predegrade",
                      "restart", "attempt_start", "run_ok"]
    restart = rows[events.index("restart")]
    assert restart["cause"] == "oom" and restart["backoff_s"] == 0.0


def test_supervisor_second_oom_escalates_classified(tmp_path):
    def doomed(i):
        raise DeviceOomError("RESOURCE_EXHAUSTED: still too big")

    journal = str(tmp_path / "recovery.jsonl")
    sup = RunSupervisor(
        RestartPolicy(max_restarts=5, backoff_seconds=0, jitter=False),
        journal=journal, sleep=lambda s: None, compile_store=None,
    )
    with pytest.raises(RestartsExhausted) as ei:
        sup.run(doomed)
    assert ei.value.cause == "oom"
    # Exactly ONE pre-degraded restart was attempted, despite the 5-deep
    # restart budget — the budget is for transients, not capacity walls.
    assert len(ei.value.failures) == 2
    rows = [json.loads(x) for x in open(journal).read().splitlines()]
    assert [r["event"] for r in rows] == [
        "attempt_start", "attempt_failed", "oom_predegrade", "restart",
        "attempt_start", "attempt_failed", "exhausted"]
    assert rows[-1]["cause"] == "oom"


def test_supervisor_oom_restart_rides_outside_transient_budget():
    """The one pre-degraded OOM restart is NOT charged against
    max_restarts: after it, genuine transients still get the full
    transient budget."""
    from photon_tpu.faults import DeviceLostError

    seq = [DeviceOomError("RESOURCE_EXHAUSTED: x"),
           DeviceLostError("lost"), DeviceLostError("lost")]
    calls = []

    def attempt(i):
        calls.append(i)
        if seq:
            raise seq.pop(0)
        return "ok"

    sup = RunSupervisor(
        RestartPolicy(max_restarts=2, backoff_seconds=0, jitter=False),
        sleep=lambda s: None, compile_store=None,
    )
    assert sup.run(attempt) == "ok"
    # 1 free OOM restart + the 2 budgeted transient restarts = 4 attempts.
    assert calls == [0, 1, 2, 3]


def test_supervisor_zero_budget_never_restarts_oom():
    """max_restarts=0 means never restart — the OOM carve-out does not
    override an operator's explicit no-restart policy."""
    def doomed(i):
        raise DeviceOomError("RESOURCE_EXHAUSTED: x")

    sup = RunSupervisor(RestartPolicy(max_restarts=0),
                        sleep=lambda s: None, compile_store=None)
    with pytest.raises(RestartsExhausted) as ei:
        sup.run(doomed)
    assert len(ei.value.failures) == 1 and ei.value.cause == "oom"


def test_supervisor_without_journal_preserves_outer_journal(tmp_path):
    """A journal-less supervisor must not detach a journal some outer
    component registered (set_journal save/restore contract)."""
    outer = RecoveryJournal(str(tmp_path / "outer.jsonl"))
    mg.set_journal(outer)
    sup = RunSupervisor(RestartPolicy(max_restarts=0),
                        sleep=lambda s: None, compile_store=None)
    assert sup.run(lambda i: "ok") == "ok"
    mg.downshifter("t.outer").absorb(DeviceOomError("b"),
                                     before="a", after="b")
    rows = open(outer.path).read().splitlines()
    assert rows and json.loads(rows[0])["event"] == "oom_downshift"


def test_run_with_recovery_skips_backoff_on_oom():
    sleeps = []
    calls = []

    def attempt(i):
        calls.append(i)
        if i == 0:
            raise DeviceOomError("boom")
        return "ok"

    assert run_with_recovery(
        attempt, RestartPolicy(max_restarts=1, backoff_seconds=7.0,
                               jitter=False),
        sleep=sleeps.append) == "ok"
    assert calls == [0, 1] and sleeps == []


# ------------------------------------------------- per-site ladder drills


def _re_problem():
    from photon_tpu.functions.problem import GLMOptimizationProblem
    from photon_tpu.optim import (
        OptimizerConfig,
        OptimizerType,
        RegularizationContext,
        RegularizationType,
    )
    from photon_tpu.types import TaskType

    return GLMOptimizationProblem(
        task=TaskType.LOGISTIC_REGRESSION,
        optimizer_config=OptimizerConfig(max_iterations=40),
        optimizer_type=OptimizerType.LBFGS,
        regularization=RegularizationContext(RegularizationType.L2),
        reg_weight=0.5,
    )


def _uniform_entity_data(rng, n_entities=9, rows=6, global_dim=50, k=6):
    """Every entity gets the same row count -> ONE bucket, so the faulted
    dispatch is the bucket whose downshift tier we control."""
    idx_rows, val_rows, labels, keys = [], [], [], []
    for e in range(n_entities):
        support = rng.choice(global_dim, size=8, replace=False)
        for _ in range(rows):
            cols = rng.choice(support, size=k, replace=False)
            vals = rng.normal(size=k)
            idx_rows.append(cols.astype(np.int64))
            val_rows.append(vals)
            labels.append(float(rng.random() < 0.5))
            keys.append(f"u{e}")
    return (np.asarray(idx_rows), np.asarray(val_rows),
            np.asarray(labels, np.float32), np.asarray(keys, object))


def test_re_solve_oom_downshifts_one_tier_same_result(rng, monkeypatch):
    """The tentpole RE drill at unit scale: an injected device_oom on the
    bucket dispatch downshifts one blessed chunk tier (sticky), completes
    WITHOUT escalating, and the coefficients match the uninterrupted run
    to 1e-12 (PR 4 chunked==full equivalence) — only the chunk tier
    changed, the solver family did not."""
    monkeypatch.setenv("PHOTON_RE_CHUNK_LADDER", "4,8")
    from photon_tpu.data.random_effect import build_random_effect_dataset
    from photon_tpu.game import train_random_effects

    problem = _re_problem()
    idx, val, labels, keys = _uniform_entity_data(rng, n_entities=9)
    # f64: the 1e-12 equivalence bound is a double-precision claim (the
    # f32 chunked-vs-full delta is batched-GEMM reassociation noise).
    ds = build_random_effect_dataset(
        "userId", keys, idx, val, labels, global_dim=50, dtype=np.float64)
    assert len(ds.buckets) == 1 and ds.buckets[0].n_entities == 9
    offsets = jnp.zeros((ds.n_rows,), jnp.float64)
    ref, _ = train_random_effects(problem, ds, offsets)

    mg.reset_state()
    before = REGISTRY.counter("oom_downshifts_total").value(
        site="re.solve", cause="oom")
    plan = FaultPlan(seed=0, specs=[
        FaultSpec(site="re.solve", error="device_oom", count=1)])
    with active_plan(plan) as inj:
        shifted, _ = train_random_effects(problem, ds, offsets)
    assert inj.fired("re.solve") == 1
    assert REGISTRY.counter("oom_downshifts_total").value(
        site="re.solve", cause="oom") == before + 1
    # Sticky: the surviving (downshifted) plan is recorded for the run —
    # one chunk tier below the full 9-entity bucket on the 4/8 ladder.
    assert mg.sticky_plan("re.solve") == {"chunk": 8, "solver": None}
    for a, b in zip(shifted.bucket_coefs, ref.bucket_coefs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-12, rtol=0)


def test_measured_routing_oom_demotes_to_sticky_static_tier(
    rng, monkeypatch,
):
    """Under PHOTON_RE_ROUTING=measured an OOM out of the measured plan
    (or its calibration race) demotes to one tier below the STATIC plan —
    never a no-op or an up-shift — sticky, so later buckets skip the
    measured winner that cannot fit."""
    monkeypatch.setenv("PHOTON_RE_ROUTING", "measured")
    monkeypatch.setenv("PHOTON_RE_CHUNK_LADDER", "4,8")
    from photon_tpu.data.random_effect import build_random_effect_dataset
    from photon_tpu.game import solver_routing, train_random_effects

    solver_routing.reset_process_table()
    problem = _re_problem()
    idx, val, labels, keys = _uniform_entity_data(rng, n_entities=9)
    ds = build_random_effect_dataset(
        "userId", keys, idx, val, labels, global_dim=50)
    offsets = jnp.zeros((ds.n_rows,), jnp.float32)
    plan = FaultPlan(seed=0, specs=[
        FaultSpec(site="re.solve", error="device_oom", count=1)])
    try:
        with active_plan(plan) as inj:
            model, _ = train_random_effects(problem, ds, offsets)
        assert inj.fired("re.solve") == 1
        sticky = mg.sticky_plan("re.solve")
        assert sticky is not None and sticky["chunk"] == 8  # 9 -> tier 8
        assert np.isfinite(np.asarray(model.bucket_coefs[0])).all()
        # Later fits run on the sticky plan without re-racing the winner.
        train_random_effects(problem, ds, offsets)
    finally:
        solver_routing.reset_process_table()


def test_re_solve_oom_ladder_exhausted_escalates(rng, monkeypatch):
    """A device_oom on EVERY dispatch drains the ladder and the original
    classified error escalates (journaled exhaustion, no infinite loop)."""
    monkeypatch.setenv("PHOTON_RE_CHUNK_LADDER", "4,8")
    monkeypatch.setenv("PHOTON_OOM_MAX_DOWNSHIFTS", "8")
    from photon_tpu.data.random_effect import build_random_effect_dataset
    from photon_tpu.game import train_random_effects
    from tests.test_random_effect import _make_entity_data

    problem = _re_problem()
    idx, val, labels, keys = _make_entity_data(rng, n_entities=6)
    ds = build_random_effect_dataset(
        "userId", keys, idx, val, labels, global_dim=50)
    offsets = jnp.zeros((ds.n_rows,), jnp.float32)
    plan = FaultPlan(seed=0, specs=[
        FaultSpec(site="re.solve", error="device_oom")])  # every dispatch
    with active_plan(plan):
        with pytest.raises(DeviceOomError):
            train_random_effects(problem, ds, offsets)
    assert bg.classify_backend_error(
        DeviceOomError("x")) == bg.CAUSE_OOM  # escalates classified


def test_ooc_rechunk_preserves_rows():
    from photon_tpu.optim.out_of_core import ChunkedGLMData

    rng = np.random.default_rng(0)
    n, dim, k = 37, 20, 4
    idx = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    val = rng.normal(size=(n, k)).astype(np.float32)
    labels = rng.normal(size=n).astype(np.float32)
    data = ChunkedGLMData.from_arrays(idx, val, labels, dim, chunk_rows=16)
    half = data.rechunk(2)
    assert half.chunk_rows == 8 and half.n_rows == n
    assert half.n_chunks == 2 * data.n_chunks
    # Row content (true rows + ghost convention) is preserved exactly.
    def flatten(d):
        i = np.concatenate([c.idx for c in d.chunks])
        v = np.concatenate([c.val for c in d.chunks])
        w = np.concatenate([np.asarray(x) for x in d.weights])
        real = w > 0
        return i[real], v[real]

    for a, b in zip(flatten(data), flatten(half)):
        np.testing.assert_array_equal(a, b)
    with pytest.raises(ValueError):
        ChunkedGLMData.from_arrays(idx, val, labels, dim,
                                   chunk_rows=1).rechunk(2)


def test_ooc_oom_halves_chunk_rows_and_completes():
    """An injected device_oom on a streamed chunk feed re-cuts the data at
    half chunk_rows and the solve completes at the same optimum (the cut
    only changes accumulation grouping)."""
    from photon_tpu.optim.out_of_core import ChunkedGLMData, OutOfCoreLBFGS
    from photon_tpu.ops.losses import loss_for_task
    from photon_tpu.types import TaskType

    rng = np.random.default_rng(1)
    n, dim, k = 256, 30, 4
    idx = rng.integers(0, dim, size=(n, k)).astype(np.int32)
    val = (rng.normal(size=(n, k)) / 2).astype(np.float32)
    z = val.sum(1)
    labels = (rng.random(n) < 1 / (1 + np.exp(-z))).astype(np.float32)
    solver = OutOfCoreLBFGS(
        loss=loss_for_task(TaskType.LOGISTIC_REGRESSION), l2_weight=1.0)
    data = ChunkedGLMData.from_arrays(idx, val, labels, dim, chunk_rows=64)
    # The reference is the uninterrupted run AT THE DOWNSHIFTED CUT: the
    # re-cut regroups f32 accumulation, so the honest equivalence claim is
    # against the same chunking (the optimum agrees to solver tolerance
    # either way — asserted on the objective below).
    ref = solver.optimize(
        ChunkedGLMData.from_arrays(idx, val, labels, dim, chunk_rows=32),
        jnp.zeros(dim))
    full = solver.optimize(data, jnp.zeros(dim))

    before = REGISTRY.counter("oom_downshifts_total").value(
        site="optim.ooc_chunk", cause="oom")
    data2 = ChunkedGLMData.from_arrays(idx, val, labels, dim, chunk_rows=64)
    plan = FaultPlan(seed=0, specs=[
        FaultSpec(site="optim.ooc_chunk", error="device_oom", count=1)])
    with active_plan(plan) as inj:
        shifted = solver.optimize(data2, jnp.zeros(dim))
    assert inj.fired("optim.ooc_chunk") == 1
    assert REGISTRY.counter("oom_downshifts_total").value(
        site="optim.ooc_chunk", cause="oom") == before + 1
    # Bit-identical to the uninterrupted half-cut run (the fault fired
    # before any step committed), and at the same optimum as the full cut.
    np.testing.assert_array_equal(np.asarray(shifted.x), np.asarray(ref.x))
    assert abs(float(shifted.value) - float(full.value)) < 1e-6
    np.testing.assert_allclose(np.asarray(shifted.x), np.asarray(full.x),
                               atol=2e-4, rtol=0)


def test_ooc_oom_exhausted_escalates(monkeypatch):
    monkeypatch.setenv("PHOTON_OOM_MAX_DOWNSHIFTS", "1")
    from photon_tpu.optim.out_of_core import ChunkedGLMData, OutOfCoreLBFGS
    from photon_tpu.ops.losses import loss_for_task
    from photon_tpu.types import TaskType

    rng = np.random.default_rng(2)
    idx = rng.integers(0, 10, size=(32, 3)).astype(np.int32)
    val = rng.normal(size=(32, 3)).astype(np.float32)
    labels = rng.normal(size=32).astype(np.float32)
    solver = OutOfCoreLBFGS(
        loss=loss_for_task(TaskType.LINEAR_REGRESSION), l2_weight=1.0)
    data = ChunkedGLMData.from_arrays(idx, val, labels, 10, chunk_rows=16)
    plan = FaultPlan(seed=0, specs=[
        FaultSpec(site="optim.ooc_chunk", error="device_oom")])  # always
    with active_plan(plan):
        with pytest.raises(DeviceOomError):
            solver.optimize(data, jnp.zeros(10))
