"""Elastic multi-host mesh (docs/scaling.md §"Multi-host mesh").

Covers the membership layer (formation, barriers, part-keyed reduction,
empty shards), the coordinated shrink ledger, ragged file-shard
assignment, classified bring-up failure under --distributed-policy, the
per-host cost-table merge, beacon-liveness gauges, and the fleet report's
Mesh section. The full SIGKILL + rejoin drill over real processes runs in
``scripts/multihost_smoke.py`` (a ci.sh stage); the slow marker here holds
the subprocess N=1 vs N=2 coefficient-equality check.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from photon_tpu.parallel.distributed import (
    DistributedInitError,
    HostLostError,
    MeshMembership,
    assign_file_shards,
    process_file_shard,
    resolve_distributed_policy,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Shard assignment (pure)
# ---------------------------------------------------------------------------


class TestFileShardAssignment:
    def test_ragged_round_robin(self):
        got = assign_file_shards(["a", "b", "c", "d", "e"], [0, 1, 2])
        assert got == {0: ["a", "d"], 1: ["b", "e"], 2: ["c"]}
        assert sorted(f for fs in got.values() for f in fs) == [
            "a", "b", "c", "d", "e"]

    def test_fewer_files_than_hosts_keeps_empty_hosts(self):
        # The empty-shard host must still get a key: membership, not data
        # volume, defines who participates in collectives.
        got = assign_file_shards(["only"], [0, 1, 2])
        assert got == {0: ["only"], 1: [], 2: []}

    def test_empty_file_list(self):
        assert assign_file_shards([], [0, 1]) == {0: [], 1: []}

    def test_unsorted_members_assign_deterministically(self):
        a = assign_file_shards(["a", "b", "c"], [2, 0, 1])
        b = assign_file_shards(["a", "b", "c"], [0, 1, 2])
        assert a == b

    def test_process_file_shard_slices_this_hosts_files(self):
        # Single process: the whole list. (index, count) without files.
        assert process_file_shard(["x", "y"]) == ["x", "y"]
        assert process_file_shard() == (0, 1)


# ---------------------------------------------------------------------------
# host_lost classification + bring-up policy
# ---------------------------------------------------------------------------


class TestHostLostClassification:
    def test_host_lost_error_classifies(self):
        from photon_tpu.runtime.backend_guard import (
            CAUSE_HOST_LOST,
            classify_backend_error,
        )

        e = HostLostError([2], "reduction 's1-r0' epoch 0")
        assert classify_backend_error(e) == CAUSE_HOST_LOST
        assert e.dead == [2]

    def test_barrier_timeout_text_classifies(self):
        from photon_tpu.runtime.backend_guard import (
            CAUSE_HOST_LOST,
            classify_backend_error,
        )

        msg = RuntimeError("mesh barrier timed out at step-3")
        assert classify_backend_error(msg) == CAUSE_HOST_LOST


class TestDistributedPolicy:
    def test_resolve_precedence_and_validation(self, monkeypatch):
        monkeypatch.delenv("PHOTON_DISTRIBUTED_POLICY", raising=False)
        assert resolve_distributed_policy() == "strict"
        monkeypatch.setenv("PHOTON_DISTRIBUTED_POLICY", "degrade")
        assert resolve_distributed_policy() == "degrade"
        assert resolve_distributed_policy("strict") == "strict"  # arg wins
        with pytest.raises(ValueError):
            resolve_distributed_policy("yolo")

    def test_strict_failure_is_classified_and_journaled(
            self, tmp_path, monkeypatch):
        import jax

        from photon_tpu.parallel.distributed import initialize_distributed
        from photon_tpu.supervisor import RecoveryJournal

        def boom(**kwargs):
            raise RuntimeError("coordinator unreachable: connect failed")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        journal = RecoveryJournal(str(tmp_path / "recovery.jsonl"))
        with pytest.raises(DistributedInitError) as ei:
            initialize_distributed(
                "localhost:9999", num_processes=2, process_id=0,
                policy="strict", journal=journal)
        assert ei.value.cause  # classified, never a bare traceback
        rows = [json.loads(line) for line in
                (tmp_path / "recovery.jsonl").read_text().splitlines()]
        assert [r["event"] for r in rows] == ["distributed_init_failed"]
        assert rows[0]["policy"] == "strict" and rows[0]["cause"]

    def test_degrade_continues_single_host(self, tmp_path, monkeypatch):
        import jax

        from photon_tpu.parallel.distributed import initialize_distributed
        from photon_tpu.supervisor import RecoveryJournal

        def boom(**kwargs):
            raise RuntimeError("coordinator unreachable: connect failed")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        journal = RecoveryJournal(str(tmp_path / "recovery.jsonl"))
        assert initialize_distributed(
            "localhost:9999", num_processes=2, process_id=0,
            policy="degrade", journal=journal) is False
        rows = [json.loads(line) for line in
                (tmp_path / "recovery.jsonl").read_text().splitlines()]
        assert rows and rows[0]["event"] == "distributed_init_failed"

    def test_driver_flag_registered(self):
        import argparse

        from photon_tpu.cli.params import add_distributed_flags

        p = argparse.ArgumentParser()
        add_distributed_flags(p)
        assert p.parse_args([]).distributed_policy == "strict"
        assert p.parse_args(
            ["--distributed-policy", "degrade"]).distributed_policy \
            == "degrade"


# ---------------------------------------------------------------------------
# Membership protocol (threads standing in for hosts)
# ---------------------------------------------------------------------------


def _run_hosts(fn, n, **kwargs):
    """Run fn(host_id) on n threads; re-raise the first failure."""
    errors = []

    def wrap(h):
        try:
            fn(h)
        except BaseException as e:  # noqa: BLE001 - surfaced to pytest
            errors.append((h, e))

    threads = [threading.Thread(target=wrap, args=(h,), daemon=True)
               for h in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    if errors:
        raise errors[0][1]
    assert not any(t.is_alive() for t in threads), "host thread hung"


class TestMeshMembership:
    def test_form_barrier_reduce_with_empty_shard(self, tmp_path):
        """3 hosts over 2 parts: the part-less host still barriers and
        receives the full reduction — membership defines the collective."""
        results = {}

        def host(h):
            mem = MeshMembership(
                str(tmp_path), h, 3, ["a", "b"],
                beat_seconds=0.1, stale_factor=30.0, wait_timeout=30.0)
            try:
                mem.start(form_timeout=30.0)
                assert mem.members == [0, 1, 2]
                assert mem.epoch == 0
                payloads = {pid: {"v": np.full(2, float(h) + 1.0)}
                            for pid in mem.my_files()}
                out = mem.reduce_parts("t0", payloads)
                folded = sum(out[p]["v"][0] for p in mem.part_ids)
                mem.barrier("done")
                results[h] = (mem.my_files(), folded)
            finally:
                mem.stop()

        _run_hosts(host, 3)
        assert results[0][0] == ["a"] and results[1][0] == ["b"]
        assert results[2][0] == []  # empty shard, still participated
        # Every host folded the SAME global value (owner 0 wrote 1.0 for
        # part a, owner 1 wrote 2.0 for part b).
        assert {r[1] for r in results.values()} == {3.0}

    def test_shrink_journals_loss_and_redistributes(self, tmp_path):
        """Survivor-coordinated shrink: classified host_lost row, epoch
        row, and the dead host's parts reassigned to the survivor."""
        formed = threading.Event()
        die = threading.Event()
        out = {}

        def host(h):
            mem = MeshMembership(
                str(tmp_path), h, 2, ["a", "b"],
                beat_seconds=0.1, stale_factor=3.0, wait_timeout=30.0)
            mem.start(form_timeout=30.0)
            if h == 1:  # this host "dies": beacons stop, thread exits
                formed.wait(30.0)
                mem.hb.stop()
                die.set()
                return
            formed.set()
            die.wait(30.0)
            time.sleep(0.5)  # let host 1's last beat age past staleness
            try:
                mem.handle_loss([1])
                out["members"] = mem.members
                out["files"] = mem.files
                out["epoch"] = mem.epoch
            finally:
                mem.stop()

        _run_hosts(host, 2)
        assert out["members"] == [0]
        assert out["files"] == {0: ["a", "b"]}
        assert out["epoch"] == 1
        rows = [json.loads(line) for line in
                (tmp_path / "mesh-epochs.jsonl").read_text().splitlines()]
        events = [r["event"] for r in rows]
        assert events[0] == "mesh_formed"
        assert "host_lost" in events and "mesh_shrunk" in events
        lost = rows[events.index("host_lost")]
        assert lost["host"] == 1 and lost["cause"] == "host_lost"
        moved = [r for r in rows if r["event"] == "shard_redistributed"
                 and r.get("kind") == "files"]
        assert moved and moved[0]["host"] == 0 and "b" in moved[0]["items"]

    def test_shrink_budget_exhaustion_escalates(self, tmp_path):
        die = threading.Event()

        def host(h):
            mem = MeshMembership(
                str(tmp_path), h, 2, ["a"],
                beat_seconds=0.1, stale_factor=3.0, wait_timeout=30.0,
                max_shrinks=0)
            mem.start(form_timeout=30.0)
            if h == 1:
                mem.hb.stop()
                die.set()
                return
            die.wait(30.0)
            time.sleep(0.5)
            try:
                with pytest.raises(RuntimeError, match="budget exhausted"):
                    mem.handle_loss([1])
            finally:
                mem.stop()

        _run_hosts(host, 2)
        rows = [json.loads(line) for line in
                (tmp_path / "mesh-epochs.jsonl").read_text().splitlines()]
        assert any(r["event"] == "recovery_budget_exhausted"
                   and r["scope"] == "mesh_shrink" for r in rows)


# ---------------------------------------------------------------------------
# Beacon gauges + fleet report Mesh section
# ---------------------------------------------------------------------------


class TestBeaconGauges:
    def test_export_peer_gauges(self, tmp_path):
        from photon_tpu.obs.metrics import REGISTRY
        from photon_tpu.supervisor import Heartbeat

        hb = Heartbeat(str(tmp_path), process_id=0, memory_guard=None,
                       peer_gauges=[0, 1])
        hb.beat_once()
        hb.export_peer_gauges()
        snap = REGISTRY.snapshot()["host_beacon_age_seconds"]
        assert 0.0 <= snap["0"] < 5.0   # own beacon: fresh
        assert snap["1"] == -1.0        # never beaconed: sentinel, not 0


class TestFleetMeshSection:
    def _ledger_rows(self):
        return [
            {"event": "mesh_formed", "epoch": 0, "t": 1.0,
             "members": [0, 1], "files": {"0": ["a"], "1": ["b"]}},
            {"event": "host_lost", "host": 1, "cause": "host_lost",
             "epoch": 0, "t": 2.0, "time": "T1",
             "beacon_age_seconds": 1.5},
            {"event": "mesh_shrunk", "epoch": 1, "t": 2.1,
             "members": [0], "files": {"0": ["a", "b"]}, "dead": [1]},
            {"event": "shard_redistributed", "kind": "files", "host": 0,
             "t": 2.2, "items": ["b"]},
            {"event": "host_rejoined", "host": 1, "epoch": 1, "t": 3.0,
             "time": "T2"},
            {"event": "mesh_grown", "epoch": 2, "t": 3.1,
             "members": [0, 1], "files": {"0": ["a"], "1": ["b"]},
             "joined": [1]},
        ]

    def test_mesh_section_shape(self):
        from photon_tpu.obs.analysis.report import _mesh_section

        snap = {"host_beacon_age_seconds": {"0": 0.1, "1": 7.5}}
        mesh = _mesh_section(snap, self._ledger_rows())
        assert mesh["epoch"] == 2 and mesh["members"] == [0, 1]
        assert mesh["host_losses"] == [
            {"host": 1, "epoch": 0, "time": "T1",
             "beacon_age_seconds": 1.5}]
        assert mesh["rejoins"][0]["host"] == 1
        assert mesh["redistributions"] == 1
        assert mesh["beacon_age_seconds"]["1"] == 7.5

    def test_no_mesh_run_has_no_section(self):
        from photon_tpu.obs.analysis.report import _mesh_section

        assert _mesh_section({}, []) is None
        assert _mesh_section({"other_metric": 1.0},
                             [{"event": "run_restart"}]) is None

    def test_report_end_to_end_renders_mesh(self, tmp_path):
        from photon_tpu.obs import fleet
        from photon_tpu.obs.analysis.report import (
            build_report,
            format_markdown,
        )
        from photon_tpu.obs.metrics import REGISTRY

        with open(tmp_path / "mesh-epochs.jsonl", "w") as f:
            for row in self._ledger_rows():
                f.write(json.dumps({"time": "T0", "pid": 1, **row}) + "\n")
        REGISTRY.gauge("host_beacon_age_seconds", "t").set(0.2, host="0")
        fleet.write_registry_shard(
            str(tmp_path / "registry.mesh-host-0.json"), role="mesh-host")
        report = build_report(str(tmp_path))
        assert report["mesh"]["members"] == [0, 1]
        md = format_markdown(report)
        assert "## Mesh" in md
        assert "host LOST: 1" in md and "host rejoined: 1" in md


# ---------------------------------------------------------------------------
# Cost-table merge
# ---------------------------------------------------------------------------


class TestCostTableMerge:
    def _table(self, tmp_path, name, entries):
        from photon_tpu.game.solver_routing import SolverCostTable

        t = SolverCostTable()
        t.load_json({"version": 1, "entries": entries})
        path = str(tmp_path / name)
        t.save(path)
        return path

    def test_merge_means_overlap_adopts_rest(self, tmp_path):
        from photon_tpu.game.solver_routing import merge_host_tables

        a = self._table(tmp_path, "solver_costs.host-0.json",
                        {"S32_P8@dev1": {"newton@256": 1.0, "lbfgs": 4.0}})
        b = self._table(tmp_path, "solver_costs.host-1.json",
                        {"S32_P8@dev1": {"newton@256": 3.0},
                         "S32_P8@dev8": {"newton@256": 9.0}})
        out = str(tmp_path / "solver_costs.merged.json")
        merged = merge_host_tables([a, b], out)
        entries = merged.to_json()["entries"]
        assert entries["S32_P8@dev1"]["newton@256"] == 2.0  # mean
        assert entries["S32_P8@dev1"]["lbfgs"] == 4.0       # adopted
        assert entries["S32_P8@dev8"]["newton@256"] == 9.0  # @devN inert
        assert os.path.exists(out)

    def test_torn_shard_skipped(self, tmp_path):
        from photon_tpu.game.solver_routing import merge_host_tables

        good = self._table(tmp_path, "solver_costs.host-0.json",
                           {"S4_P4@dev1": {"lbfgs": 2.0}})
        torn = tmp_path / "solver_costs.host-1.json"
        torn.write_text("{not json")
        merged = merge_host_tables([good, str(torn)],
                                   str(tmp_path / "merged.json"))
        assert merged.to_json()["entries"]["S4_P4@dev1"]["lbfgs"] == 2.0


# ---------------------------------------------------------------------------
# Elastic trainer: membership-invariant coefficients (subprocess, slow)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestElasticEquality:
    def test_two_hosts_match_one_host_bitwise(self, tmp_path):
        """The whole elasticity argument in one assert: the global
        reduction folds per-part partials in canonical part order, so the
        optimizer trajectory cannot depend on the part->host assignment.
        N=1 and N=2 worker processes must produce IDENTICAL coefficients
        (the SIGKILL mid-run version lives in scripts/multihost_smoke.py)."""
        from photon_tpu.parallel.elastic import make_synthetic_parts

        manifest = make_synthetic_parts(
            str(tmp_path / "data"), n_parts=4, rows_per_part=12, dim=5,
            n_entities=6)

        def run(n_hosts):
            mesh = str(tmp_path / f"mesh{n_hosts}")
            procs = [subprocess.Popen(
                [sys.executable, "-m", "photon_tpu.parallel.elastic",
                 "--mesh-dir", mesh, "--host-id", str(h),
                 "--hosts", str(n_hosts), "--manifest", manifest,
                 "--sweeps", "1", "--max-iterations", "8",
                 "--beat-seconds", "0.5", "--stale-factor", "20"],
                cwd=REPO, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            ) for h in range(n_hosts)]
            for p in procs:
                out, err = p.communicate(timeout=240)
                assert p.returncode == 0, err[-800:]
            return np.load(os.path.join(mesh, "final-model.npz"))

        one, two = run(1), run(2)
        np.testing.assert_array_equal(one["w"], two["w"])
        np.testing.assert_array_equal(one["re_scores"], two["re_scores"])
